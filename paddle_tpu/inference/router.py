"""Multi-engine serving router: data-parallel ServingEngine replicas
behind least-loaded admission, with replica-death requeue.

Reference analog: the fleet serving deployments that front N identical
AnalysisPredictor workers with a dispatcher (the multi-stream serving
shape of inference/api/analysis_predictor.h:94's `clone()` contract —
one predictor per stream, a router above). Here each replica is a full
continuous-batching ServingEngine (inference/serving.py) — its own slot
pool, KV cache (dense or paged), compiled executables and SLO
guardrails — and the router is a THIN host-side layer: it owns no
device state, so it composes with everything the engine already does
(paged KV, chunked prefill, speculative decode, tensor-parallel
`mesh=` — a router over tp-sharded engines is the dp x tp serving
story).

Scheduling: `submit` places each request on the live replica with the
smallest load (in-slot + queued requests — join-shortest-queue, the
classic latency-optimal dispatch for identical servers); a replica that
refuses (its own `max_queue` backpressure or page-pool admission) falls
through to the next-least-loaded, and only when EVERY live replica
refuses does the router queue (bounded by ITS `max_queue` with the same
reject/shed_oldest policies, reusing BackpressureError). The engines'
own machinery keeps doing what PR 5 built — deadlines, TTL, cancel,
quarantine, self-healing — the router only translates inner terminals
to its own EXACTLY-ONCE resolution.

Replica death (`kill_replica`, or any exception escaping a replica's
step — the engines self-heal internally, so an escape means the
replica is gone): every un-terminal request mapped to the dead replica
moves to a survivor. The router tries LIVE MIGRATION first — host
snapshot of the request's KV (pages or cache rows) + decode-state
mirror via `ServingEngine.snapshot_request`, restored into a
survivor's pool through the admission-reservation path
(`restore_request`), so the stream continues with ZERO re-prefilled
tokens and a continuation bit-identical to an undisturbed engine.
Only when no snapshot exists (the replica died mid-step, the request
was still mid-prefill, or no survivor has capacity) does it fall back
to the original requeue-replay: the request REQUEUES at the head of
the router queue and replays FROM SCRATCH (`RouterRequest.tokens` is
reset so the final list never duplicates) — at-least-once token
DELIVERY with exactly-once TERMINAL resolution either way. Requests
already terminal on the dead replica stay resolved (never re-run); a
death with zero live replicas left resolves everything "evicted"
(never limbo). Every death leaves a flight-recorder dump.

Prefill/decode disaggregation (`roles=`): replicas can specialize —
"prefill" replicas take ALL new admissions (chunked prefill and the
first tokens), and the per-tick handoff sweep moves each stream to a
"decode" replica the moment its prefill finishes, through the SAME
live-migration seam deaths use (zero re-prefilled tokens:
serving.prefills stays equal to requests submitted; bit-identical
continuation). A prefill flood therefore queues against the prefill
pool while decode replicas keep their tick cadence — decode ITL p99
stays flat (tools/bench_serving.py --role-split is the A/B). Roles
are placement PREFERENCES, not availability constraints: when the
fleet degrades to one capability, prefill_targets/decode_targets fall
back to the full dispatchable set (chaos_serving prefill_role_death
pins that requests still resolve).

Fleet elasticity (`spawn_replica` / `drain_replica`) is the seam
`inference/autoscale.py`'s control loop drives: spawn adds a warm
engine to the rotation; drain flips a replica to DRAINING (admits
nothing, keeps stepping, live requests migrate out where capacity
allows) and the router releases it at the first tick it holds no
work. Deadlines re-scope to the REMAINING budget at every (re)
dispatch and migration — an exhausted budget resolves "timeout"
immediately instead of burning a survivor's slot. `testing/faults.py`
injects `replica_preempt@T:R` / `migrate_raise` through this module's
`_FAULT_HOOK` (consulted once per router tick).

Observability: serving.router.* monitor names — the replicas_live
gauge, the requeues/rejected counters, per-replica queue-depth gauges
(serving.router.queue_depth.r<i>) and dispatch counters
(serving.router.dispatched.r<i> — the admission-balance observable) —
summarized by tools/telemetry_report.py's "router" block;
tools/bench_serving.py --router measures aggregate tokens/s vs replica
count and tools/chaos_serving.py's replica_death scenario is the
executable acceptance test.
"""
from __future__ import annotations

import collections
import time
from typing import List, Optional, Sequence

import numpy as np

from .serving import (BackpressureError, PoolExhaustedError,
                      ServingEngine, TERMINAL_REASONS)
from ..profiler import monitor

__all__ = ["EngineRouter", "RouterRequest", "create_router"]

# testing/faults.py installs a callable here: called once per router
# tick as _FAULT_HOOK(tick) -> dict of actions, e.g.
# {"replica_preempt": idx} (kill replica idx, migration-first) or
# {"raise_migrate": True} (the NEXT migration attempt fails once and
# takes the requeue-replay fallback). None in production.
_FAULT_HOOK = None


class RouterRequest:
    """One generation request riding through the router. Mirrors the
    engine Request surface the schedulers and chaos checks read
    (tokens / done / finish_reason / slot / cancel()); `replica` is the
    index currently serving it (None while queued), `requeues` counts
    replica-death migrations."""

    __slots__ = ("id", "prompt", "max_new_tokens", "temperature",
                 "top_k", "eos_id", "deadline_s", "deadline_ticks",
                 "tokens", "done", "finish_reason", "replica",
                 "requeues", "t_submit", "_tick_submit", "_inner",
                 "_router", "trace")

    def __init__(self, req_id, prompt, max_new_tokens, temperature,
                 top_k, eos_id, deadline_s, deadline_ticks):
        self.id = req_id
        self.prompt = prompt
        self.max_new_tokens = max_new_tokens
        self.temperature = temperature
        self.top_k = top_k
        self.eos_id = eos_id
        self.deadline_s = deadline_s
        self.deadline_ticks = deadline_ticks
        self.tokens: List[int] = []
        self.done = False
        self.finish_reason: Optional[str] = None
        self.replica: Optional[int] = None
        self.requeues = 0
        self.t_submit = 0.0
        self._tick_submit = 0
        self._inner = None              # live engine Request, if placed
        self._router = None
        self.trace = None               # RequestTrace (tracing=True) —
        #                                 ONE tree across dispatch/replay

    @property
    def slot(self):
        """The engine slot currently decoding this request (None while
        queued or terminal) — the surface chaos_serving's
        check_terminal reads."""
        inner = self._inner
        return None if inner is None else inner.slot

    def cancel(self) -> bool:
        r = self._router
        return False if r is None else r.cancel(self)

    def __repr__(self):
        return (f"RouterRequest(id={self.id}, replica={self.replica}, "
                f"gen={len(self.tokens)}/{self.max_new_tokens}, "
                f"requeues={self.requeues}, done={self.done})")


ROLES = ("any", "prefill", "decode")


class _Replica:
    def __init__(self, idx: int, eng: ServingEngine, role: str = "any"):
        if role not in ROLES:
            raise ValueError(f"replica role {role!r} (any|prefill|decode)")
        self.idx = idx
        self.eng = eng
        # disaggregation role: "prefill" replicas admit new requests
        # (chunked prefill + first tokens) and hand mid-decode streams
        # off to "decode" replicas; "any" does both. The role is a
        # ROUTER placement preference — the engine underneath always
        # runs whatever it holds, so a request on a prefill replica
        # keeps decoding in place until a handoff slot frees (no stall)
        self.role = role
        self.alive = True
        self.draining = False           # admits nothing, still stepped
        self.inner = {}                 # inner request id -> RouterRequest
        self.m_depth = monitor.gauge(f"serving.router.queue_depth.r{idx}")
        self.m_disp = monitor.counter(f"serving.router.dispatched.r{idx}")

    @property
    def can_prefill(self) -> bool:
        return self.role != "decode"

    @property
    def can_decode(self) -> bool:
        return self.role != "prefill"

    def load(self) -> int:
        """In-flight demand: occupied slots (active or mid-prefill) +
        the engine's own admission queue."""
        eng = self.eng
        return (sum(1 for r in eng._slot_req if r is not None)
                + len(eng._queue))


class EngineRouter:
    """Least-loaded admission over N ServingEngine replicas.

    >>> router = create_router(params, cfg, family="gpt", replicas=2)
    >>> req = router.submit(prompt_ids, max_new_tokens=32)
    >>> while router.has_work():
    ...     for r, tok in router.step():
    ...         ...

    `step()` advances EVERY live replica one engine tick and returns
    the merged (request, token) emissions; `generate` wraps
    submit+drain like the engine's. Greedy streams are bit-identical
    to a single engine serving the same request (engine streams are
    slot/batch-invariant, and replicas share params + seed); sampled
    streams are reproducible per (replica, submission order) but not
    router-placement-invariant — the engine folds ITS request id into
    the PRNG stream."""

    def __init__(self, engines: Sequence[ServingEngine],
                 max_queue: int = 0, queue_policy: str = "reject",
                 concurrent: bool = True, tracing: bool = False,
                 clock=None, roles: Optional[Sequence[str]] = None):
        if not engines:
            raise ValueError("EngineRouter needs >= 1 engine replica")
        if queue_policy not in ("reject", "shed_oldest"):
            raise ValueError(f"queue_policy {queue_policy!r} "
                             "(reject|shed_oldest)")
        # prefill/decode disaggregation (docs/serving.md §Disaggregation):
        # roles aligns with `engines`; None = homogeneous "any" fleet
        # (the pre-role behavior, bit-for-bit). A role-split fleet must
        # start with both capabilities present — degradation below that
        # is handled at dispatch time (availability beats specialization)
        if roles is not None:
            roles = list(roles)
            if len(roles) != len(engines):
                raise ValueError(f"roles ({len(roles)}) must match "
                                 f"engines ({len(engines)})")
            if not any(r != "decode" for r in roles):
                raise ValueError("role split needs >= 1 prefill-capable "
                                 "replica (any|prefill)")
            if not any(r != "prefill" for r in roles):
                raise ValueError("role split needs >= 1 decode-capable "
                                 "replica (any|decode)")
        else:
            roles = ["any"] * len(engines)
        self.replicas = [_Replica(i, e, role=r)
                         for i, (e, r) in enumerate(zip(engines, roles))]
        self.max_queue = int(max_queue)       # bound on the ROUTER queue
        self.queue_policy = queue_policy
        # concurrent=True steps the replicas in parallel threads: each
        # tick's device work runs in the backend's own pool and the
        # blocking host pull releases the GIL, so R replicas' ticks
        # OVERLAP — the source of the aggregate-throughput win on one
        # host (each engine is only ever touched by its own worker per
        # tick; all router bookkeeping stays on the calling thread, so
        # emission order is deterministic: replica index, slot order)
        self.concurrent = bool(concurrent)
        self._exec = None                     # lazy, one worker/replica
        self._pending: collections.deque = collections.deque()
        self._next_id = 0
        self._ticks = 0
        # injectable clock (seconds, perf_counter-like) — deadline
        # re-scoping and dispatch-latency math read ONLY this, so
        # tests drive wall-budget trajectories deterministically
        self._clock = clock if clock is not None else time.perf_counter
        self._migrate_raise = False           # injected migrate_raise
        from ..profiler import flight_recorder
        self._flight = flight_recorder.recorder()
        # request-scoped tracing (profiler/tracing): the router mints
        # the trace at ITS submit and passes it down through engine
        # submit(_trace=), so router admission, dispatch, replica death
        # (severed subtree + replay link) and the terminal resolution
        # all land in one span tree per request
        self._tracer = None
        if tracing:
            from ..profiler import tracing as _tracing
            self._tracer = _tracing.tracer()
        # dispatch latency is a distribution (the router half of queue
        # wait) — histogram, not a last-write-wins gauge
        self._m_disp_ms = monitor.histogram("serving.router.dispatch_ms")
        self._m_live = monitor.gauge("serving.router.replicas_live")
        self._m_pending = monitor.gauge("serving.router.pending")
        self._m_requeue = monitor.counter("serving.router.requeues")
        self._m_rej = monitor.counter("serving.router.rejected")
        self._m_sub = monitor.counter("serving.router.requests_submitted")
        self._m_done = monitor.counter("serving.router.requests_completed")
        self._m_deaths = monitor.counter("serving.router.replica_deaths")
        # live-migration observables (serving.autoscale.* namespace —
        # the autoscaler adds scale_out/scale_in/replicas_target there;
        # telemetry_report groups the whole prefix into one block)
        self._m_mig = monitor.counter("serving.autoscale.migrations")
        self._m_mig_fb = monitor.counter(
            "serving.autoscale.migrate_fallbacks")
        self._m_mig_bytes = monitor.gauge(
            "serving.autoscale.migrated_pages_bytes")
        self._mig_bytes = 0                   # cumulative KV bytes moved
        # prefill->decode stream handoffs (the disaggregation seam) —
        # a subset of serving.autoscale.migrations
        self._m_handoff = monitor.counter("serving.router.handoffs")
        self._m_live.set(len(self.replicas))

    # ------------------------------------------------------- observables
    def live(self) -> List[_Replica]:
        """Replicas still being STEPPED (includes draining ones — they
        keep serving their in-flight requests until released)."""
        return [r for r in self.replicas if r.alive]

    def dispatchable(self) -> List[_Replica]:
        """Replicas that admit NEW work: live and not draining — the
        placement set for dispatch and migration targets."""
        return [r for r in self.replicas if r.alive and not r.draining]

    def prefill_targets(self) -> List[_Replica]:
        """Dispatchable replicas whose role admits NEW requests
        (prefill-capable). Falls back to the FULL dispatchable set when
        the role split has degraded to zero prefill-capable replicas —
        role purity is a latency preference, never an availability
        constraint (the prefill_role_death drill pins this)."""
        caps = [r for r in self.dispatchable() if r.can_prefill]
        return caps if caps else self.dispatchable()

    def decode_targets(self) -> List[_Replica]:
        """Dispatchable replicas whose role holds mid-decode streams —
        migration/handoff placement. Same availability fallback as
        prefill_targets."""
        caps = [r for r in self.dispatchable() if r.can_decode]
        return caps if caps else self.dispatchable()

    def has_work(self) -> bool:
        return (bool(self._pending)
                or any(r.eng.has_work() for r in self.live()))

    def stats(self) -> dict:
        """Host-side router observable: per-replica liveness/load and
        the admission balance (dispatch counts)."""
        return {"replicas": len(self.replicas),
                "replicas_live": len(self.live()),
                "replicas_dispatchable": len(self.dispatchable()),
                "pending": len(self._pending),
                "requeues": self._m_requeue.value,
                "migrations": self._m_mig.value,
                "handoffs": self._m_handoff.value,
                "per_replica": [
                    {"idx": r.idx, "alive": r.alive,
                     "draining": r.draining, "role": r.role,
                     "load": r.load() if r.alive else 0,
                     "dispatched": r.m_disp.value}
                    for r in self.replicas]}

    # --------------------------------------------------------- admission
    def submit(self, prompt, max_new_tokens: int,
               temperature: float = 0.0, top_k: int = 0,
               eos_id: Optional[int] = None,
               deadline_s: Optional[float] = None,
               deadline_ticks: Optional[int] = None) -> RouterRequest:
        """Queue one request with the least-loaded live replica (falling
        through replicas that refuse admission); raises
        BackpressureError when every replica refuses AND the router
        queue is at max_queue under "reject" (shed_oldest evicts the
        oldest router-queued request instead). PoolExhaustedError
        propagates only when NO live replica could EVER hold the
        request."""
        if not self.live():
            raise BackpressureError("no live replicas", queue_depth=0)
        req = RouterRequest(self._next_id,
                            np.asarray(prompt, np.int32).reshape(-1),
                            int(max_new_tokens), float(temperature),
                            int(top_k), eos_id,
                            None if deadline_s is None
                            else float(deadline_s),
                            None if deadline_ticks is None
                            else int(deadline_ticks))
        self._next_id += 1
        req.t_submit = self._clock()
        req._tick_submit = self._ticks
        req._router = self
        if self._tracer is not None:
            req.trace = self._tracer.trace(
                f"request-r{req.id}", request_id=req.id,
                prompt_len=int(req.prompt.shape[0]),
                max_new_tokens=req.max_new_tokens, router=True)
        # requests_submitted counts ACCEPTED requests only (same as the
        # engine's: a reject raises before anything is admitted), so
        # submitted - completed is a true in-flight gauge. A REJECTED
        # submit still owns a freshly-minted trace — finish it
        # ("rejected") before raising, or the open root span would
        # leak in the tracer forever (Tracer._open is unbounded).
        try:
            placed = self._try_dispatch(req)
        except PoolExhaustedError:
            if req.trace is not None:
                req.trace.finish("rejected", tokens=0)
            raise
        if placed:
            self._m_sub.add()
            return req
        if self.max_queue > 0 and len(self._pending) >= self.max_queue:
            if self.queue_policy == "shed_oldest":
                self._finish(self._pending.popleft(), "evicted")
            else:
                self._m_rej.add()
                if req.trace is not None:
                    req.trace.finish("rejected", tokens=0)
                raise BackpressureError(
                    f"router queue full ({len(self._pending)} waiting, "
                    f"max_queue={self.max_queue})",
                    queue_depth=len(self._pending))
        self._pending.append(req)
        self._m_pending.set(len(self._pending))
        self._m_sub.add()
        return req

    def _remaining_budget(self, req: RouterRequest):
        """Re-scope `req`'s deadlines to the budget LEFT as of now:
        wall seconds since the router submit, router ticks since the
        submit tick (router ticks double as engine ticks — every
        router step ticks every live replica once). Returns
        (deadline_s, deadline_ticks, expired)."""
        dl_s = req.deadline_s
        if dl_s is not None:
            dl_s = dl_s - (self._clock() - req.t_submit)
        dl_t = req.deadline_ticks
        if dl_t is not None:
            dl_t = dl_t - (self._ticks - req._tick_submit)
        expired = ((dl_s is not None and dl_s <= 0.0)
                   or (dl_t is not None and dl_t <= 0))
        return dl_s, dl_t, expired

    def _try_dispatch(self, req: RouterRequest) -> bool:
        """Place `req` on the least-loaded dispatchable replica that
        accepts it. Deadlines re-scope to the REMAINING budget — a
        request whose budget is already exhausted (it waited out its
        deadline in the router queue, or died with its replica at the
        deadline edge) resolves "timeout" HERE rather than being
        dispatched with a floor-clamped budget that burns a survivor
        slot for one doomed tick."""
        dl_s, dl_t, expired = self._remaining_budget(req)
        if expired:
            self._finish(req, "timeout")
            return True                   # resolved — nothing to place
        never_fits = 0
        t_disp0 = self._clock()
        # NEW requests land on prefill-capable replicas only — a
        # prefill flood then queues against the prefill pool while
        # decode replicas keep their tick cadence (ITL p99 flat)
        live = sorted(self.prefill_targets(), key=_Replica.load)
        for rep in live:
            try:
                inner = rep.eng.submit(
                    req.prompt, req.max_new_tokens,
                    temperature=req.temperature, top_k=req.top_k,
                    eos_id=req.eos_id, deadline_s=dl_s,
                    deadline_ticks=dl_t, _trace=req.trace)
            except PoolExhaustedError:
                never_fits += 1
                continue
            except BackpressureError:
                continue
            rep.inner[inner.id] = req
            rep.m_disp.add()
            self._m_disp_ms.observe((self._clock() - t_disp0) * 1e3)
            req.replica = rep.idx
            req._inner = inner
            if req.trace is not None:
                req.trace.instant("dispatch", replica=rep.idx,
                                  attempt=req.trace.attempt)
            return True
        if never_fits and never_fits == len(live):
            raise PoolExhaustedError(
                "request exceeds every live replica's page pool")
        return False

    # --------------------------------------------------------- the tick
    def step(self):
        """One router tick: dispatch what fits, advance every live
        replica one engine tick, merge their emissions onto the outer
        requests, and translate inner terminals exactly once. A replica
        whose step ESCAPES (the engine self-heals internally — an
        escape means the replica is gone) dies here and its in-flight
        requests requeue."""
        events: List[tuple] = []
        if _FAULT_HOOK is not None:
            actions = _FAULT_HOOK(self._ticks) or {}
            if actions.pop("raise_migrate", None):
                self._migrate_raise = True    # next migration fails once
            rp = actions.pop("replica_preempt", None)
            if rp is not None:
                self.kill_replica(int(rp) % len(self.replicas),
                                  reason="preempt")
        self._dispatch_pending()
        live = self.live()
        results = {}
        if self.concurrent and len(live) > 1:
            if self._exec is None:
                from concurrent.futures import ThreadPoolExecutor
                self._exec = ThreadPoolExecutor(
                    max_workers=len(self.replicas),
                    thread_name_prefix="router")
            futs = [(rep, self._exec.submit(rep.eng.step))
                    for rep in live]
            for rep, fut in futs:
                try:
                    results[rep.idx] = fut.result()
                except Exception as e:             # noqa: BLE001
                    results[rep.idx] = e
        else:
            for rep in live:
                try:
                    results[rep.idx] = rep.eng.step()
                except Exception as e:             # noqa: BLE001
                    results[rep.idx] = e
        for rep in live:
            res = results[rep.idx]
            if isinstance(res, BaseException):
                self.kill_replica(rep.idx, reason=f"step raised: {res}")
                continue
            for ireq, tok in res:
                outer = rep.inner.get(ireq.id)
                if outer is not None and not outer.done:
                    outer.tokens.append(int(tok))
                    events.append((outer, int(tok)))
            self._sweep_terminals(rep)
        self._sweep_handoffs()
        for rep in self.replicas:
            # graceful-drain release: a draining replica leaves the
            # rotation at the FIRST tick it holds no work — every
            # in-flight request it had has migrated out or resolved
            if (rep.alive and rep.draining and not rep.inner
                    and not rep.eng.has_work()):
                self._release_replica(rep)
        self._ticks += 1
        if not self.live():
            self.abort_pending("evicted")
        self._publish_gauges()
        return events

    def _dispatch_pending(self) -> None:
        while self._pending:
            head = self._pending[0]
            if head.done:                     # cancelled while queued
                self._pending.popleft()
                continue
            try:
                placed = self._try_dispatch(head)
            except PoolExhaustedError:
                # a request that was queued because the one replica
                # that could hold it backpressured now fits NO live
                # replica (that replica died): resolve it terminally —
                # PoolExhaustedError escapes submit() only, never
                # step()/drain(), and no request is left in limbo
                self._pending.popleft()
                self._finish(head, "evicted")
                continue
            if not placed:
                break
            self._pending.popleft()
        self._m_pending.set(len(self._pending))

    def _sweep_terminals(self, rep: _Replica) -> None:
        """Translate inner terminal resolutions (including ones with no
        emission this tick — timeout/cancel/evict) to the outer
        requests, exactly once."""
        for iid in [iid for iid, outer in rep.inner.items()
                    if outer._inner is not None and outer._inner.done]:
            outer = rep.inner.pop(iid)
            self._finish(outer, outer._inner.finish_reason)

    def _sweep_handoffs(self) -> None:
        """Disaggregation seam: every request on a "prefill"-role
        replica that has FINISHED its chunked prefill (it holds a live
        slot and `_pf_next is None`) moves to a decode replica through
        the live-migration path — host KV snapshot, zero re-prefilled
        tokens (`serving.prefills` stays == requests submitted),
        bit-identical stream continuation. A request that cannot move
        yet (decode pool full) keeps decoding IN PLACE on the prefill
        replica and retries next tick — handoff is a latency
        optimization, never a stall."""
        for rep in self.live():
            if rep.role != "prefill" or not rep.inner:
                continue
            targets = [r for r in self.dispatchable() if r.can_decode]
            if not targets:
                return
            for outer in list(rep.inner.values()):
                inner = outer._inner
                if (outer.done or inner is None or inner.slot is None
                        or inner._pf_next is not None):
                    continue              # queued / mid-prefill / gone
                if self._migrate(outer, rep, targets=targets):
                    self._m_handoff.add()

    def _publish_gauges(self) -> None:
        self._m_live.set(len(self.live()))
        self._m_pending.set(len(self._pending))
        for rep in self.replicas:
            rep.m_depth.set(rep.load() if rep.alive else 0)

    # ------------------------------------------------------ terminality
    def _finish(self, req: RouterRequest, reason: str) -> None:
        if req.done:
            return
        req.done = True
        req.finish_reason = reason
        req._inner = None
        if req.trace is not None:
            # exactly-once terminal span: an inner engine _finish that
            # already emitted it makes this a no-op (the once-only
            # flag); router-side terminals (requeue-then-abort, cancel
            # while pending) emit here
            req.trace.finish(reason, tokens=len(req.tokens))
        self._m_done.add()

    def cancel(self, req: RouterRequest) -> bool:
        """Resolve `req` with finish_reason "cancelled" right now.
        Returns False when it already resolved."""
        if req.done:
            return False
        if req._inner is not None:
            rep = self.replicas[req.replica]
            rep.inner.pop(req._inner.id, None)
            if rep.alive:
                req._inner.cancel()       # frees the engine slot
        else:
            try:
                self._pending.remove(req)
            except ValueError:
                pass
            self._m_pending.set(len(self._pending))
        self._finish(req, "cancelled")
        return True

    def abort_pending(self, reason: str = "evicted") -> int:
        """Resolve EVERY live request (router-queued and on-replica)
        with the terminal `reason` — no request in limbo. Returns the
        number aborted."""
        if reason not in TERMINAL_REASONS:
            raise ValueError(f"reason {reason!r} not in "
                             f"{sorted(TERMINAL_REASONS)}")
        n = 0
        while self._pending:
            self._finish(self._pending.popleft(), reason)
            n += 1
        for rep in self.replicas:
            for outer in list(rep.inner.values()):
                if outer.done:
                    continue
                if rep.alive and outer._inner is not None:
                    outer._inner.cancel()
                self._finish(outer, reason)
                n += 1
            rep.inner.clear()
        self._publish_gauges()
        return n

    # ------------------------------------------------- fleet elasticity
    def spawn_replica(self, engine: ServingEngine,
                      role: str = "any") -> int:
        """Scale OUT: add a warm `engine` to the rotation (with a
        disaggregation `role`, default "any") and return its replica
        index. The engine must share params/config with the fleet
        (greedy bit-parity across replicas assumes it); the
        autoscaler's `spawn` factory owns that construction. Joins
        the dispatchable set immediately — the next `step()` places
        queued work on it. Leaves a flight-recorder dump."""
        rep = _Replica(len(self.replicas), engine, role=role)
        self.replicas.append(rep)
        if self._exec is not None:
            # the lazy executor was sized for the OLD fleet — rebuild
            # next tick so every live replica still gets its own worker
            self._exec.shutdown(wait=False)
            self._exec = None
        self._flight.note(router_spawn=rep.idx, role=role,
                          tick=self._ticks,
                          replicas_live=len(self.live()))
        self._flight.dump("router_scale_out")
        self._publish_gauges()
        return rep.idx

    def drain_replica(self, idx: int, migrate: bool = True) -> int:
        """Scale IN, gracefully: replica `idx` stops admitting new
        work but KEEPS STEPPING its in-flight requests; the router
        releases it at the first tick it holds no work. With
        `migrate=True` every snapshot-able in-flight request moves to
        a dispatchable survivor NOW (zero re-prefill, bit-identical
        continuation) so release is typically immediate; requests that
        cannot move (mid-prefill, no capacity) simply finish in place.
        Returns the number migrated. Idempotent; flight-dumps."""
        rep = self.replicas[idx]
        if not rep.alive or rep.draining:
            return 0
        rep.draining = True
        moved = 0
        if migrate:
            for outer in [o for o in rep.inner.values() if not o.done]:
                if self._migrate(outer, rep):
                    moved += 1
        self._flight.note(router_drain=idx, migrated=moved,
                          remaining=len(rep.inner), tick=self._ticks)
        self._flight.dump("router_scale_in")
        self._publish_gauges()
        return moved

    def _release_replica(self, rep: _Replica) -> None:
        """Final step of a graceful drain: the replica holds no work —
        take it out of rotation (NOT a death: nothing requeues, the
        deaths counter stays put)."""
        rep.alive = False
        rep.draining = False
        self._flight.note(router_release=rep.idx, tick=self._ticks)
        self._flight.dump("router_release")

    # ----------------------------------------------------- live migration
    def _migrate(self, outer: RouterRequest, src: _Replica,
                 targets: Optional[List[_Replica]] = None) -> bool:
        """Move `outer` mid-decode from `src` to a dispatchable
        survivor via host KV snapshot — the zero-re-prefill path.
        Order is snapshot -> restore -> detach so any failure leaves
        the source intact (the caller falls back to requeue-replay or
        leaves the request draining in place). Deadlines re-scope to
        the remaining budget; an exhausted budget resolves "timeout"
        here. Returns True only when the request now lives on the
        target replica."""
        inner = outer._inner
        if inner is None or outer.done:
            return False
        try:
            if self._migrate_raise:
                self._migrate_raise = False
                raise RuntimeError("injected migrate_raise")
            snap = src.eng.snapshot_request(inner)
        except Exception:                      # noqa: BLE001 — fault or
            snap = None                        # mid-step corpse: fallback
        if snap is None:
            self._m_mig_fb.add()
            return False
        dl_s, dl_t, expired = self._remaining_budget(outer)
        if expired:
            src.eng.detach_request(inner)
            src.inner.pop(inner.id, None)
            self._finish(outer, "timeout")
            return True                        # resolved, nothing to move
        if targets is None:
            # a migrating request is mid-decode by construction
            # (snapshot_request refuses mid-prefill), so decode-capable
            # replicas come first; prefill-role replicas remain a
            # last-resort landing zone under fleet degradation
            targets = sorted((r for r in self.dispatchable()
                              if r is not src),
                             key=lambda r: (not r.can_decode, r.load()))
        else:
            targets = sorted((r for r in targets if r is not src),
                             key=_Replica.load)
        for dst in targets:
            try:
                new_inner = dst.eng.restore_request(
                    snap, deadline_s=dl_s, deadline_ticks=dl_t,
                    _trace=outer.trace)
            except Exception:                  # noqa: BLE001
                new_inner = None
            if new_inner is None:
                continue
            src.eng.detach_request(inner)
            src.inner.pop(inner.id, None)
            dst.inner[new_inner.id] = outer
            outer._inner = new_inner
            outer.replica = dst.idx
            self._m_mig.add()
            self._mig_bytes += int(snap.get("kv_bytes", 0))
            self._m_mig_bytes.set(self._mig_bytes)
            if outer.trace is not None:
                outer.trace.instant("migrate", src=src.idx, dst=dst.idx,
                                    kv_bytes=int(snap.get("kv_bytes", 0)))
            self._flight.note(router_migration=outer.id, src=src.idx,
                              dst=dst.idx, tick=self._ticks,
                              kv_bytes=int(snap.get("kv_bytes", 0)))
            return True
        self._m_mig_fb.add()                   # snapshot ok, no capacity
        return False

    # ---------------------------------------------------- replica death
    def kill_replica(self, idx: int, reason: str = "killed",
                     migrate: bool = True) -> int:
        """Take replica `idx` out of rotation NOW. Un-terminal requests
        it held migrate to a survivor via live KV snapshot when
        possible (`migrate=True`, zero re-prefill, bit-identical
        continuation); the rest requeue at the HEAD of the router
        queue (they waited longest) and replay from scratch — their
        token lists reset so the final streams carry no duplicates.
        Already-terminal requests stay resolved (exactly-once).
        Returns the number requeued for replay. Idempotent; leaves a
        flight-recorder dump."""
        rep = self.replicas[idx]
        if not rep.alive:
            return 0
        rep.alive = False
        rep.draining = False
        self._m_deaths.add()
        victims = [o for o in rep.inner.values() if not o.done]
        replay = []
        migrated = 0
        for outer in victims:
            # migration-first: reads the dying engine's arrays, which
            # survive `alive=False` (host process, not real hardware
            # loss) — a replica killed because its STEP raised usually
            # fails the snapshot instead and takes the replay path
            if migrate and self._migrate(outer, rep):
                migrated += 1
            elif not outer.done:               # _migrate may resolve it
                replay.append(outer)
        rep.inner.clear()
        for outer in replay:
            outer.tokens.clear()          # replay regenerates the stream
            outer._inner = None
            outer.replica = None
            outer.requeues += 1
            self._m_requeue.add()
            if outer.trace is not None:
                # close the dead replica's span subtree (tagged
                # severed, trace NOT finished) and link the replay
                # attempt — the survivor's spans carry the bumped
                # attempt index
                outer.trace.sever("replica_death", replica=idx)
                outer.trace.link_replay(replica_died=idx)
        self._pending.extendleft(reversed(replay))
        self._flight.note(router_replica_death=idx, reason=reason,
                          migrated=migrated, requeued=len(replay),
                          tick=self._ticks)
        self._flight.dump("router_replica_death")
        if not self.live():
            self.abort_pending("evicted")
        self._publish_gauges()
        return len(replay)

    # ------------------------------------------------------ conveniences
    def drain(self, max_ticks: Optional[int] = None):
        events = []
        ticks = 0
        while self.has_work():
            events.extend(self.step())
            ticks += 1
            if max_ticks is not None and ticks >= max_ticks:
                break
        return events

    def generate(self, prompts: Sequence, max_new_tokens: int,
                 temperature: float = 0.0, top_k: int = 0,
                 eos_id: Optional[int] = None,
                 deadline_s: Optional[float] = None,
                 deadline_ticks: Optional[int] = None,
                 max_ticks: Optional[int] = None) -> List[np.ndarray]:
        """Batch convenience mirroring ServingEngine.generate: submit
        every prompt, drain, resolve stragglers ("evicted" — never
        limbo), return each request's generated ids in order."""
        reqs = [self.submit(p, max_new_tokens, temperature=temperature,
                            top_k=top_k, eos_id=eos_id,
                            deadline_s=deadline_s,
                            deadline_ticks=deadline_ticks)
                for p in prompts]
        self.drain(max_ticks)
        for r in reqs:
            if not r.done:
                self.cancel(r)
                r.finish_reason = "evicted"
        return [np.asarray(r.tokens, np.int32) for r in reqs]


def create_router(params, cfg, replicas: int = 2, family: str = "gpt",
                  max_queue: int = 0, queue_policy: str = "reject",
                  concurrent: bool = True,
                  meshes: Optional[Sequence] = None,
                  tracing: bool = False, clock=None,
                  roles: Optional[Sequence[str]] = None,
                  **engine_kw) -> EngineRouter:
    """Build an EngineRouter over `replicas` identical ServingEngines
    sharing ONE param tree (read-only at decode — on a single host the
    replicas share the arrays; in a real deployment each replica's
    params live on its own devices). `meshes` optionally gives each
    replica its own tensor-parallel mesh (inference/serving.py mesh=)
    — the dp(router) x tp(engine) composition. `tracing` turns on
    request-scoped tracing at the ROUTER (the engines inherit the
    trace through dispatch — they need no tracer of their own). A
    `telemetry_jsonl=` engine kwarg fans out per replica
    (`<path>.r<i>`), so each replica streams its own serving_tick
    JSONL — the per-replica files tools/telemetry_report.py's fleet
    mode merges. `roles` (aligned with replica index, values
    any|prefill|decode) turns on prefill/decode disaggregation —
    docs/serving.md §Disaggregation."""
    if replicas < 1:
        raise ValueError(f"replicas must be >= 1; got {replicas}")
    if meshes is not None and len(meshes) != replicas:
        raise ValueError(f"meshes ({len(meshes)}) must match "
                         f"replicas ({replicas})")
    tele = engine_kw.pop("telemetry_jsonl", None)
    engines = [ServingEngine(params, cfg, family=family,
                             mesh=None if meshes is None else meshes[i],
                             telemetry_jsonl=(f"{tele}.r{i}" if tele
                                              else None),
                             **engine_kw)
               for i in range(replicas)]
    return EngineRouter(engines, max_queue=max_queue,
                        queue_policy=queue_policy, concurrent=concurrent,
                        tracing=tracing, clock=clock, roles=roles)
