"""Multi-engine serving router: data-parallel ServingEngine replicas
behind least-loaded admission, with replica-death requeue.

Reference analog: the fleet serving deployments that front N identical
AnalysisPredictor workers with a dispatcher (the multi-stream serving
shape of inference/api/analysis_predictor.h:94's `clone()` contract —
one predictor per stream, a router above). Here each replica is a full
continuous-batching ServingEngine (inference/serving.py) — its own slot
pool, KV cache (dense or paged), compiled executables and SLO
guardrails — and the router is a THIN host-side layer: it owns no
device state, so it composes with everything the engine already does
(paged KV, chunked prefill, speculative decode, tensor-parallel
`mesh=` — a router over tp-sharded engines is the dp x tp serving
story).

Scheduling: `submit` places each request on the live replica with the
smallest load (in-slot + queued requests — join-shortest-queue, the
classic latency-optimal dispatch for identical servers); a replica that
refuses (its own `max_queue` backpressure or page-pool admission) falls
through to the next-least-loaded, and only when EVERY live replica
refuses does the router queue (bounded by ITS `max_queue` with the same
reject/shed_oldest policies, reusing BackpressureError). The engines'
own machinery keeps doing what PR 5 built — deadlines, TTL, cancel,
quarantine, self-healing — the router only translates inner terminals
to its own EXACTLY-ONCE resolution.

Replica death (`kill_replica`, or any exception escaping a replica's
step — the engines self-heal internally, so an escape means the
replica is gone): every un-terminal request mapped to the dead replica
moves to a survivor. The router tries LIVE MIGRATION first — host
snapshot of the request's KV (pages or cache rows) + decode-state
mirror via `ServingEngine.snapshot_request`, restored into a
survivor's pool through the admission-reservation path
(`restore_request`), so the stream continues with ZERO re-prefilled
tokens and a continuation bit-identical to an undisturbed engine.
Only when no snapshot exists (the replica died mid-step, the request
was still mid-prefill, or no survivor has capacity) does it fall back
to the original requeue-replay: the request REQUEUES at the head of
the router queue and replays FROM SCRATCH (`RouterRequest.tokens` is
reset so the final list never duplicates) — at-least-once token
DELIVERY with exactly-once TERMINAL resolution either way. Requests
already terminal on the dead replica stay resolved (never re-run); a
death with zero live replicas left resolves everything "evicted"
(never limbo). Every death leaves a flight-recorder dump.

Prefill/decode disaggregation (`roles=`): replicas can specialize —
"prefill" replicas take ALL new admissions (chunked prefill and the
first tokens), and the per-tick handoff sweep moves each stream to a
"decode" replica the moment its prefill finishes, through the SAME
live-migration seam deaths use (zero re-prefilled tokens:
serving.prefills stays equal to requests submitted; bit-identical
continuation). A prefill flood therefore queues against the prefill
pool while decode replicas keep their tick cadence — decode ITL p99
stays flat (tools/bench_serving.py --role-split is the A/B). Roles
are placement PREFERENCES, not availability constraints: when the
fleet degrades to one capability, prefill_targets/decode_targets fall
back to the full dispatchable set (chaos_serving prefill_role_death
pins that requests still resolve).

Fleet elasticity (`spawn_replica` / `drain_replica`) is the seam
`inference/autoscale.py`'s control loop drives: spawn adds a warm
engine to the rotation; drain flips a replica to DRAINING (admits
nothing, keeps stepping, live requests migrate out where capacity
allows) and the router releases it at the first tick it holds no
work. Deadlines re-scope to the REMAINING budget at every (re)
dispatch and migration — an exhausted budget resolves "timeout"
immediately instead of burning a survivor's slot. `testing/faults.py`
injects `replica_preempt@T:R` / `migrate_raise` through this module's
`_FAULT_HOOK` (consulted once per router tick).

Multi-tenant overload resilience (docs/serving.md §Tenancy, brownout &
durability): `admission=` plugs an `inference/admission.py`
AdmissionController in front of the queue — per-tenant token-bucket
quotas (a typed QuotaExceededError with the exact retry-after),
weighted-fair dispatch ordering (priority classes strictly first, then
tenant virtual time), and PREEMPT-TO-HOST: when a high-priority submit
finds no capacity, the lowest-priority mid-decode victim is SUSPENDED
— its KV parks in a router-owned HostKVTier via the same
snapshot/restore seam migration uses — and resumes later with zero
re-prefilled tokens. `journal_dir=` adds the crash-safe request WAL
(`inference/journal.py`): every accepted request is durable before
submit() returns, every terminal lands in `_finish`, and a router
rebuilt over the same directory REPLAYS the crashed process's
un-terminal requests (at-least-once prefill, exactly-once terminal).
The brownout ladder (`inference/brownout.py`) drives the degrade
levers this module exposes: `set_spec_drafts` / `set_resume_hold` +
`suspend_lowest_class` / `shed_oldest_pending`.

Observability: serving.router.* monitor names — the replicas_live
gauge, the requeues/rejected counters, per-replica queue-depth gauges
(serving.router.queue_depth.r<i>) and dispatch counters
(serving.router.dispatched.r<i> — the admission-balance observable) —
summarized by tools/telemetry_report.py's "router" block;
tools/bench_serving.py --router measures aggregate tokens/s vs replica
count and tools/chaos_serving.py's replica_death scenario is the
executable acceptance test.
"""
from __future__ import annotations

import collections
import time
from typing import List, Optional, Sequence

import numpy as np

from .admission import AdmissionController, QuotaExceededError
from .host_kv import HostKVTier
from .serving import (BackpressureError, PoolExhaustedError,
                      ServingEngine, TERMINAL_REASONS)
from ..profiler import monitor

__all__ = ["EngineRouter", "RouterRequest", "create_router"]

# testing/faults.py installs a callable here: called once per router
# tick as _FAULT_HOOK(tick) -> dict of actions, e.g.
# {"replica_preempt": idx} (kill replica idx, migration-first),
# {"raise_migrate": True} (the NEXT migration attempt fails once and
# takes the requeue-replay fallback) or {"quota_flood": n} (burst n
# low-priority flood-tenant submissions). None in production.
_FAULT_HOOK = None


class RouterRequest:
    """One generation request riding through the router. Mirrors the
    engine Request surface the schedulers and chaos checks read
    (tokens / done / finish_reason / slot / cancel()); `replica` is the
    index currently serving it (None while queued), `requeues` counts
    replica-death migrations."""

    __slots__ = ("id", "prompt", "max_new_tokens", "temperature",
                 "top_k", "eos_id", "deadline_s", "deadline_ticks",
                 "tokens", "done", "finish_reason", "replica",
                 "requeues", "t_submit", "_tick_submit", "_inner",
                 "_router", "trace", "tenant", "priority", "suspended")

    def __init__(self, req_id, prompt, max_new_tokens, temperature,
                 top_k, eos_id, deadline_s, deadline_ticks,
                 tenant: str = "default", priority: int = 0):
        self.id = req_id
        self.prompt = prompt
        self.max_new_tokens = max_new_tokens
        self.temperature = temperature
        self.top_k = top_k
        self.eos_id = eos_id
        self.deadline_s = deadline_s
        self.deadline_ticks = deadline_ticks
        self.tokens: List[int] = []
        self.done = False
        self.finish_reason: Optional[str] = None
        self.replica: Optional[int] = None
        self.requeues = 0
        self.t_submit = 0.0
        self._tick_submit = 0
        self._inner = None              # live engine Request, if placed
        self._router = None
        self.trace = None               # RequestTrace (tracing=True) —
        #                                 ONE tree across dispatch/replay
        # multi-tenant admission labels (inference/admission.py) +
        # the preempt-to-host parked state (KV in the router's tier)
        self.tenant = str(tenant)
        self.priority = int(priority)
        self.suspended = False

    @property
    def slot(self):
        """The engine slot currently decoding this request (None while
        queued or terminal) — the surface chaos_serving's
        check_terminal reads."""
        inner = self._inner
        return None if inner is None else inner.slot

    def cancel(self) -> bool:
        r = self._router
        return False if r is None else r.cancel(self)

    def __repr__(self):
        return (f"RouterRequest(id={self.id}, replica={self.replica}, "
                f"gen={len(self.tokens)}/{self.max_new_tokens}, "
                f"requeues={self.requeues}, done={self.done})")


ROLES = ("any", "prefill", "decode")


class _Replica:
    def __init__(self, idx: int, eng: ServingEngine, role: str = "any"):
        if role not in ROLES:
            raise ValueError(f"replica role {role!r} (any|prefill|decode)")
        self.idx = idx
        self.eng = eng
        # disaggregation role: "prefill" replicas admit new requests
        # (chunked prefill + first tokens) and hand mid-decode streams
        # off to "decode" replicas; "any" does both. The role is a
        # ROUTER placement preference — the engine underneath always
        # runs whatever it holds, so a request on a prefill replica
        # keeps decoding in place until a handoff slot frees (no stall)
        self.role = role
        self.alive = True
        self.draining = False           # admits nothing, still stepped
        self.inner = {}                 # inner request id -> RouterRequest
        self.m_depth = monitor.gauge(f"serving.router.queue_depth.r{idx}")
        self.m_disp = monitor.counter(f"serving.router.dispatched.r{idx}")

    @property
    def can_prefill(self) -> bool:
        return self.role != "decode"

    @property
    def can_decode(self) -> bool:
        return self.role != "prefill"

    def load(self) -> int:
        """In-flight demand: occupied slots (active or mid-prefill) +
        the engine's own admission queue."""
        eng = self.eng
        return (sum(1 for r in eng._slot_req if r is not None)
                + len(eng._queue))


class EngineRouter:
    """Least-loaded admission over N ServingEngine replicas.

    >>> router = create_router(params, cfg, family="gpt", replicas=2)
    >>> req = router.submit(prompt_ids, max_new_tokens=32)
    >>> while router.has_work():
    ...     for r, tok in router.step():
    ...         ...

    `step()` advances EVERY live replica one engine tick and returns
    the merged (request, token) emissions; `generate` wraps
    submit+drain like the engine's. Greedy streams are bit-identical
    to a single engine serving the same request (engine streams are
    slot/batch-invariant, and replicas share params + seed); sampled
    streams are reproducible per (replica, submission order) but not
    router-placement-invariant — the engine folds ITS request id into
    the PRNG stream."""

    def __init__(self, engines: Sequence[ServingEngine],
                 max_queue: int = 0, queue_policy: str = "reject",
                 concurrent: bool = True, tracing: bool = False,
                 clock=None, roles: Optional[Sequence[str]] = None,
                 admission=None, journal_dir: Optional[str] = None,
                 suspend_tier_bytes: int = 1 << 28):
        if not engines:
            raise ValueError("EngineRouter needs >= 1 engine replica")
        if queue_policy not in ("reject", "shed_oldest"):
            raise ValueError(f"queue_policy {queue_policy!r} "
                             "(reject|shed_oldest)")
        # prefill/decode disaggregation (docs/serving.md §Disaggregation):
        # roles aligns with `engines`; None = homogeneous "any" fleet
        # (the pre-role behavior, bit-for-bit). A role-split fleet must
        # start with both capabilities present — degradation below that
        # is handled at dispatch time (availability beats specialization)
        if roles is not None:
            roles = list(roles)
            if len(roles) != len(engines):
                raise ValueError(f"roles ({len(roles)}) must match "
                                 f"engines ({len(engines)})")
            if not any(r != "decode" for r in roles):
                raise ValueError("role split needs >= 1 prefill-capable "
                                 "replica (any|prefill)")
            if not any(r != "prefill" for r in roles):
                raise ValueError("role split needs >= 1 decode-capable "
                                 "replica (any|decode)")
        else:
            roles = ["any"] * len(engines)
        self.replicas = [_Replica(i, e, role=r)
                         for i, (e, r) in enumerate(zip(engines, roles))]
        self.max_queue = int(max_queue)       # bound on the ROUTER queue
        self.queue_policy = queue_policy
        # concurrent=True steps the replicas in parallel threads: each
        # tick's device work runs in the backend's own pool and the
        # blocking host pull releases the GIL, so R replicas' ticks
        # OVERLAP — the source of the aggregate-throughput win on one
        # host (each engine is only ever touched by its own worker per
        # tick; all router bookkeeping stays on the calling thread, so
        # emission order is deterministic: replica index, slot order)
        self.concurrent = bool(concurrent)
        self._exec = None                     # lazy, one worker/replica
        self._pending: collections.deque = collections.deque()
        self._next_id = 0
        self._ticks = 0
        # injectable clock (seconds, perf_counter-like) — deadline
        # re-scoping and dispatch-latency math read ONLY this, so
        # tests drive wall-budget trajectories deterministically
        self._clock = clock if clock is not None else time.perf_counter
        self._migrate_raise = False           # injected migrate_raise
        from ..profiler import flight_recorder
        self._flight = flight_recorder.recorder()
        # request-scoped tracing (profiler/tracing): the router mints
        # the trace at ITS submit and passes it down through engine
        # submit(_trace=), so router admission, dispatch, replica death
        # (severed subtree + replay link) and the terminal resolution
        # all land in one span tree per request
        self._tracer = None
        if tracing:
            from ..profiler import tracing as _tracing
            self._tracer = _tracing.tracer()
        # dispatch latency is a distribution (the router half of queue
        # wait) — histogram, not a last-write-wins gauge
        self._m_disp_ms = monitor.histogram("serving.router.dispatch_ms")
        self._m_live = monitor.gauge("serving.router.replicas_live")
        self._m_pending = monitor.gauge("serving.router.pending")
        self._m_requeue = monitor.counter("serving.router.requeues")
        self._m_rej = monitor.counter("serving.router.rejected")
        self._m_sub = monitor.counter("serving.router.requests_submitted")
        self._m_done = monitor.counter("serving.router.requests_completed")
        self._m_deaths = monitor.counter("serving.router.replica_deaths")
        # live-migration observables (serving.autoscale.* namespace —
        # the autoscaler adds scale_out/scale_in/replicas_target there;
        # telemetry_report groups the whole prefix into one block)
        self._m_mig = monitor.counter("serving.autoscale.migrations")
        self._m_mig_fb = monitor.counter(
            "serving.autoscale.migrate_fallbacks")
        self._m_mig_bytes = monitor.gauge(
            "serving.autoscale.migrated_pages_bytes")
        self._mig_bytes = 0                   # cumulative KV bytes moved
        # prefill->decode stream handoffs (the disaggregation seam) —
        # a subset of serving.autoscale.migrations
        self._m_handoff = monitor.counter("serving.router.handoffs")
        # ---------------------------------------- multi-tenant admission
        # admission= is an AdmissionController or a {tenant: TenantQuota}
        # dict (sugar — wrapped on the router's clock); None keeps the
        # pre-tenancy dispatch bit-for-bit (pure FCFS, no quotas, no
        # preemption)
        if admission is None or isinstance(admission,
                                           AdmissionController):
            self._admission = admission
        else:
            self._admission = AdmissionController(dict(admission),
                                                  clock=self._clock)
        # preempt-to-host parking lot: a suspended request's KV lives in
        # this LRU tier (host RAM, bounded) keyed ("suspend", outer.id);
        # everything else about it sits in _suspended as a kv-less
        # snapshot dict. A park the LRU evicts falls back to
        # requeue-replay at resume time — at-least-once, never limbo.
        self._suspend_tier = HostKVTier(int(suspend_tier_bytes))
        self._suspended: dict = {}            # id -> (outer, meta snap)
        self._resume_hold = False             # brownout level-2 latch
        self._m_susp = monitor.gauge("serving.router.suspended")
        # ------------------------------------------ crash-safe journal
        # construction RECOVERS: un-terminal admits from a previous
        # process replay through the router queue under their ORIGINAL
        # ids (the id counter seeds past the WAL's horizon, so fresh
        # and replayed ids never collide and the journal's terminal set
        # stays duplicate-free)
        self._journal = None
        self._m_replay = monitor.counter("serving.journal.replays")
        if journal_dir is not None:
            from .journal import RequestJournal
            self._journal = RequestJournal(journal_dir)
            self._next_id = self._journal.next_id
            for rec in self._journal.replayable():
                req = RouterRequest(
                    int(rec["id"]),
                    np.asarray(rec["prompt"], np.int32).reshape(-1),
                    int(rec["max_new_tokens"]),
                    float(rec["temperature"]), int(rec["top_k"]),
                    rec.get("eos_id"), None, None,
                    tenant=rec.get("tenant", "default"),
                    priority=int(rec.get("priority", 0)))
                req.t_submit = self._clock()
                req._router = self
                if self._tracer is not None:
                    req.trace = self._tracer.trace(
                        f"request-r{req.id}", request_id=req.id,
                        prompt_len=int(req.prompt.shape[0]),
                        max_new_tokens=req.max_new_tokens,
                        router=True, replayed=True)
                self._pending.append(req)
                self._m_replay.add()
                self._m_sub.add()
            self._m_pending.set(len(self._pending))
        self._m_live.set(len(self.replicas))

    # ------------------------------------------------------- observables
    def live(self) -> List[_Replica]:
        """Replicas still being STEPPED (includes draining ones — they
        keep serving their in-flight requests until released)."""
        return [r for r in self.replicas if r.alive]

    def dispatchable(self) -> List[_Replica]:
        """Replicas that admit NEW work: live and not draining — the
        placement set for dispatch and migration targets."""
        return [r for r in self.replicas if r.alive and not r.draining]

    def prefill_targets(self) -> List[_Replica]:
        """Dispatchable replicas whose role admits NEW requests
        (prefill-capable). Falls back to the FULL dispatchable set when
        the role split has degraded to zero prefill-capable replicas —
        role purity is a latency preference, never an availability
        constraint (the prefill_role_death drill pins this)."""
        caps = [r for r in self.dispatchable() if r.can_prefill]
        return caps if caps else self.dispatchable()

    def decode_targets(self) -> List[_Replica]:
        """Dispatchable replicas whose role holds mid-decode streams —
        migration/handoff placement. Same availability fallback as
        prefill_targets."""
        caps = [r for r in self.dispatchable() if r.can_decode]
        return caps if caps else self.dispatchable()

    def has_work(self) -> bool:
        return (bool(self._pending) or bool(self._suspended)
                or any(r.eng.has_work() for r in self.live()))

    def stats(self) -> dict:
        """Host-side router observable: per-replica liveness/load and
        the admission balance (dispatch counts)."""
        out = {"replicas": len(self.replicas),
                "replicas_live": len(self.live()),
                "replicas_dispatchable": len(self.dispatchable()),
                "pending": len(self._pending),
                "suspended": len(self._suspended),
                "requeues": self._m_requeue.value,
                "migrations": self._m_mig.value,
                "handoffs": self._m_handoff.value,
                "per_replica": [
                    {"idx": r.idx, "alive": r.alive,
                     "draining": r.draining, "role": r.role,
                     "load": r.load() if r.alive else 0,
                     "dispatched": r.m_disp.value}
                    for r in self.replicas]}
        if self._admission is not None:
            out["admission"] = self._admission.stats()
        if self._journal is not None:
            out["journal"] = {
                "admits": len(self._journal.admits),
                "ends": len(self._journal.ends),
                "replayable": len(self._journal.replayable())}
        return out

    # --------------------------------------------------------- admission
    def submit(self, prompt, max_new_tokens: int,
               temperature: float = 0.0, top_k: int = 0,
               eos_id: Optional[int] = None,
               deadline_s: Optional[float] = None,
               deadline_ticks: Optional[int] = None,
               tenant: str = "default",
               priority: int = 0) -> RouterRequest:
        """Queue one request with the least-loaded live replica (falling
        through replicas that refuse admission); raises
        BackpressureError when every replica refuses AND the router
        queue is at max_queue under "reject" (shed_oldest evicts the
        oldest router-queued request instead). PoolExhaustedError
        propagates only when NO live replica could EVER hold the
        request. Under `admission=`, `tenant`'s token bucket is charged
        the worst-case cost first (QuotaExceededError carries the exact
        retry-after; nothing is deducted on reject), and a `priority`-
        class request that finds no capacity SUSPENDS the lowest
        strictly-lower-priority mid-decode victim to the host tier and
        takes its slot. Under `journal_dir=`, acceptance is durable
        (the admit record is fsynced before this returns) and every
        rejection leaves an end-only journal record."""
        if not self.live():
            raise BackpressureError("no live replicas", queue_depth=0)
        req = RouterRequest(self._next_id,
                            np.asarray(prompt, np.int32).reshape(-1),
                            int(max_new_tokens), float(temperature),
                            int(top_k), eos_id,
                            None if deadline_s is None
                            else float(deadline_s),
                            None if deadline_ticks is None
                            else int(deadline_ticks),
                            tenant=tenant, priority=priority)
        self._next_id += 1
        req.t_submit = self._clock()
        req._tick_submit = self._ticks
        req._router = self
        if self._tracer is not None:
            req.trace = self._tracer.trace(
                f"request-r{req.id}", request_id=req.id,
                prompt_len=int(req.prompt.shape[0]),
                max_new_tokens=req.max_new_tokens, router=True)
        # requests_submitted counts ACCEPTED requests only (same as the
        # engine's: a reject raises before anything is admitted), so
        # submitted - completed is a true in-flight gauge. EVERY reject
        # path below runs _reject first: the freshly-minted trace
        # finishes ("rejected") before raising — or the open root span
        # would leak in the tracer forever (Tracer._open is unbounded)
        # — and the journal gets its end-only record (the satellite
        # trace-leak contract: one terminal trace + one journal
        # terminal per rejection, THEN the error propagates).
        if self._admission is not None:
            cost = int(req.prompt.shape[0]) + req.max_new_tokens
            try:
                self._admission.charge(req.tenant, cost)
            except QuotaExceededError:
                self._admission.counter("rejected", req.tenant).add()
                self._m_rej.add()
                self._reject(req)
                raise
        if self._admission is not None and not self._has_free_slot():
            # preempt-to-host: no replica can SLOT this request right
            # now (engines with unbounded queues never refuse — they
            # would just queue it behind the very streams it outranks),
            # so park the lowest strictly-lower-priority mid-decode
            # victim (KV to the host tier, zero re-prefill on resume)
            # and let the dispatch below take the freed slot
            victim = self._admission.preempt_candidate(
                self._inflight(), req.priority)
            if victim is not None and self._suspend(victim):
                self._admission._m_pre.add()
        try:
            placed = self._try_dispatch(req)
        except PoolExhaustedError:
            self._reject(req)
            raise
        if placed:
            self._accept(req)
            return req
        if self.max_queue > 0 and len(self._pending) >= self.max_queue:
            if self.queue_policy == "shed_oldest":
                self._finish(self._pending.popleft(), "evicted")
            else:
                self._m_rej.add()
                self._reject(req)
                raise BackpressureError(
                    f"router queue full ({len(self._pending)} waiting, "
                    f"max_queue={self.max_queue})",
                    queue_depth=len(self._pending))
        self._pending.append(req)
        self._m_pending.set(len(self._pending))
        self._accept(req)
        return req

    def _accept(self, req: RouterRequest) -> None:
        """The accepted-submission bookkeeping shared by the placed and
        queued paths: the fsynced journal admit record (acceptance is
        durable before submit() returns), the per-tenant admitted
        counter, the submitted counter."""
        if self._journal is not None:
            self._journal.record_admit(
                req.id, [int(t) for t in req.prompt],
                req.max_new_tokens, req.temperature, req.top_k,
                req.eos_id, req.tenant, req.priority)
        if self._admission is not None:
            self._admission.counter("admitted", req.tenant).add()
        self._m_sub.add()

    def _reject(self, req: RouterRequest) -> None:
        """The rejected-submission bookkeeping run BEFORE the error
        propagates: exactly one terminal trace span and one end-only
        journal record (recovery ignores end-only ids — a rejection was
        client-visible as an exception and must never replay)."""
        if req.trace is not None:
            req.trace.finish("rejected", tokens=0)
        if self._journal is not None:
            self._journal.record_terminal(req.id, "rejected", tokens=0)

    def _remaining_budget(self, req: RouterRequest):
        """Re-scope `req`'s deadlines to the budget LEFT as of now:
        wall seconds since the router submit, router ticks since the
        submit tick (router ticks double as engine ticks — every
        router step ticks every live replica once). Returns
        (deadline_s, deadline_ticks, expired)."""
        dl_s = req.deadline_s
        if dl_s is not None:
            dl_s = dl_s - (self._clock() - req.t_submit)
        dl_t = req.deadline_ticks
        if dl_t is not None:
            dl_t = dl_t - (self._ticks - req._tick_submit)
        expired = ((dl_s is not None and dl_s <= 0.0)
                   or (dl_t is not None and dl_t <= 0))
        return dl_s, dl_t, expired

    def _try_dispatch(self, req: RouterRequest) -> bool:
        """Place `req` on the least-loaded dispatchable replica that
        accepts it. Deadlines re-scope to the REMAINING budget — a
        request whose budget is already exhausted (it waited out its
        deadline in the router queue, or died with its replica at the
        deadline edge) resolves "timeout" HERE rather than being
        dispatched with a floor-clamped budget that burns a survivor
        slot for one doomed tick."""
        dl_s, dl_t, expired = self._remaining_budget(req)
        if expired:
            self._finish(req, "timeout")
            return True                   # resolved — nothing to place
        never_fits = 0
        t_disp0 = self._clock()
        # NEW requests land on prefill-capable replicas only — a
        # prefill flood then queues against the prefill pool while
        # decode replicas keep their tick cadence (ITL p99 flat)
        live = sorted(self.prefill_targets(), key=_Replica.load)
        for rep in live:
            try:
                inner = rep.eng.submit(
                    req.prompt, req.max_new_tokens,
                    temperature=req.temperature, top_k=req.top_k,
                    eos_id=req.eos_id, deadline_s=dl_s,
                    deadline_ticks=dl_t, _trace=req.trace)
            except PoolExhaustedError:
                never_fits += 1
                continue
            except BackpressureError:
                continue
            rep.inner[inner.id] = req
            rep.m_disp.add()
            self._m_disp_ms.observe((self._clock() - t_disp0) * 1e3)
            req.replica = rep.idx
            req._inner = inner
            if self._admission is not None:
                # stride update: the tenant's virtual time advances by
                # the work it just got placed, over its weight
                self._admission.note_dispatch(
                    req.tenant,
                    int(req.prompt.shape[0]) + req.max_new_tokens)
            if req.trace is not None:
                req.trace.instant("dispatch", replica=rep.idx,
                                  attempt=req.trace.attempt)
            return True
        if never_fits and never_fits == len(live):
            raise PoolExhaustedError(
                "request exceeds every live replica's page pool")
        return False

    # --------------------------------------------------------- the tick
    def step(self):
        """One router tick: dispatch what fits, advance every live
        replica one engine tick, merge their emissions onto the outer
        requests, and translate inner terminals exactly once. A replica
        whose step ESCAPES (the engine self-heals internally — an
        escape means the replica is gone) dies here and its in-flight
        requests requeue."""
        events: List[tuple] = []
        if _FAULT_HOOK is not None:
            actions = _FAULT_HOOK(self._ticks) or {}
            if actions.pop("raise_migrate", None):
                self._migrate_raise = True    # next migration fails once
            rp = actions.pop("replica_preempt", None)
            if rp is not None:
                self.kill_replica(int(rp) % len(self.replicas),
                                  reason="preempt")
            qf = actions.pop("quota_flood", None)
            if qf is not None:
                self._inject_flood(int(qf))
        # suspended streams resume BEFORE cold admissions dispatch —
        # they are mid-flight (their tokens are owed) and their slot
        # claim predates everything in the queue
        self._resume_suspended()
        self._dispatch_pending()
        live = self.live()
        results = {}
        if self.concurrent and len(live) > 1:
            if self._exec is None:
                from concurrent.futures import ThreadPoolExecutor
                self._exec = ThreadPoolExecutor(
                    max_workers=len(self.replicas),
                    thread_name_prefix="router")
            futs = [(rep, self._exec.submit(rep.eng.step))
                    for rep in live]
            for rep, fut in futs:
                try:
                    results[rep.idx] = fut.result()
                except Exception as e:             # noqa: BLE001
                    results[rep.idx] = e
        else:
            for rep in live:
                try:
                    results[rep.idx] = rep.eng.step()
                except Exception as e:             # noqa: BLE001
                    results[rep.idx] = e
        for rep in live:
            res = results[rep.idx]
            if isinstance(res, BaseException):
                self.kill_replica(rep.idx, reason=f"step raised: {res}")
                continue
            for ireq, tok in res:
                outer = rep.inner.get(ireq.id)
                if outer is not None and not outer.done:
                    outer.tokens.append(int(tok))
                    events.append((outer, int(tok)))
            self._sweep_terminals(rep)
        self._sweep_handoffs()
        for rep in self.replicas:
            # graceful-drain release: a draining replica leaves the
            # rotation at the FIRST tick it holds no work — every
            # in-flight request it had has migrated out or resolved
            if (rep.alive and rep.draining and not rep.inner
                    and not rep.eng.has_work()):
                self._release_replica(rep)
        self._ticks += 1
        if not self.live():
            self.abort_pending("evicted")
        self._publish_gauges()
        return events

    def _dispatch_pending(self) -> None:
        if self._admission is not None and len(self._pending) > 1:
            # weighted-fair head-of-line: reorder the queue by
            # (priority DESC, tenant virtual-time ASC, id) — the FCFS
            # loop below then runs unchanged, so admission=None keeps
            # the pre-tenancy dispatch bit-for-bit
            self._pending = collections.deque(
                self._admission.order(self._pending))
        while self._pending:
            head = self._pending[0]
            if head.done:                     # cancelled while queued
                self._pending.popleft()
                continue
            try:
                placed = self._try_dispatch(head)
            except PoolExhaustedError:
                # a request that was queued because the one replica
                # that could hold it backpressured now fits NO live
                # replica (that replica died): resolve it terminally —
                # PoolExhaustedError escapes submit() only, never
                # step()/drain(), and no request is left in limbo
                self._pending.popleft()
                self._finish(head, "evicted")
                continue
            if not placed:
                break
            self._pending.popleft()
        self._m_pending.set(len(self._pending))

    def _sweep_terminals(self, rep: _Replica) -> None:
        """Translate inner terminal resolutions (including ones with no
        emission this tick — timeout/cancel/evict) to the outer
        requests, exactly once."""
        for iid in [iid for iid, outer in rep.inner.items()
                    if outer._inner is not None and outer._inner.done]:
            outer = rep.inner.pop(iid)
            self._finish(outer, outer._inner.finish_reason)

    def _sweep_handoffs(self) -> None:
        """Disaggregation seam: every request on a "prefill"-role
        replica that has FINISHED its chunked prefill (it holds a live
        slot and `_pf_next is None`) moves to a decode replica through
        the live-migration path — host KV snapshot, zero re-prefilled
        tokens (`serving.prefills` stays == requests submitted),
        bit-identical stream continuation. A request that cannot move
        yet (decode pool full) keeps decoding IN PLACE on the prefill
        replica and retries next tick — handoff is a latency
        optimization, never a stall."""
        for rep in self.live():
            if rep.role != "prefill" or not rep.inner:
                continue
            targets = [r for r in self.dispatchable() if r.can_decode]
            if not targets:
                return
            for outer in list(rep.inner.values()):
                inner = outer._inner
                if (outer.done or inner is None or inner.slot is None
                        or inner._pf_next is not None):
                    continue              # queued / mid-prefill / gone
                if self._migrate(outer, rep, targets=targets):
                    self._m_handoff.add()

    def _publish_gauges(self) -> None:
        self._m_live.set(len(self.live()))
        self._m_pending.set(len(self._pending))
        for rep in self.replicas:
            rep.m_depth.set(rep.load() if rep.alive else 0)

    # ------------------------------------ tenancy, suspension, brownout
    def _has_free_slot(self) -> bool:
        """Whether any prefill-capable replica could SLOT a new request
        immediately — a free slot AND an empty engine queue (anything
        already engine-queued claims the slot first)."""
        for rep in self.prefill_targets():
            eng = rep.eng
            if (not eng._queue
                    and any(r is None for r in eng._slot_req)):
                return True
        return False

    def _inflight(self) -> List[RouterRequest]:
        """Un-terminal requests currently HOLDING an engine slot on a
        live replica — the preemption candidate set (queued and
        suspended requests hold nothing worth preempting)."""
        out = []
        for rep in self.live():
            out.extend(o for o in rep.inner.values()
                       if not o.done and o._inner is not None)
        return out

    def _suspend(self, outer: RouterRequest) -> bool:
        """Park `outer` mid-decode: host KV snapshot (the migration
        seam) into the router's HostKVTier, kv-less metadata into
        `_suspended`, slot and pages freed NOW. Returns False when no
        snapshot exists (mid-prefill / already gone) — the caller picks
        another victim or gives up. A KV block bigger than the whole
        tier (put refuses) falls back to requeue-replay immediately:
        capacity still frees, delivery degrades to at-least-once."""
        inner = outer._inner
        if inner is None or outer.done:
            return False
        rep = self.replicas[outer.replica]
        try:
            snap = rep.eng.snapshot_request(inner)
        except Exception:                      # noqa: BLE001
            snap = None
        if snap is None:
            return False
        kv_k = snap.pop("kv_k")
        kv_v = snap.pop("kv_v")
        rep.eng.detach_request(inner)
        rep.inner.pop(inner.id, None)
        outer._inner = None
        outer.replica = None
        if self._suspend_tier.put(("suspend", outer.id), kv_k, kv_v):
            outer.suspended = True
            self._suspended[outer.id] = (outer, snap)
            if self._admission is not None:
                self._admission.counter("suspended", outer.tenant).add()
            if outer.trace is not None:
                outer.trace.instant(
                    "suspend", kv_bytes=int(snap.get("kv_bytes", 0)))
            self._flight.note(router_suspend=outer.id,
                              priority=outer.priority,
                              tenant=outer.tenant, tick=self._ticks)
        else:
            self._replay_requeue(outer, "suspend_spill")
        self._m_susp.set(len(self._suspended))
        return True

    def _resume_suspended(self) -> int:
        """Un-park suspended streams onto replicas with capacity (id
        order — longest-parked first), restoring through the SAME seam
        migration uses: zero re-prefilled tokens, bit-identical greedy
        continuation. Held entirely while the brownout latch
        (`set_resume_hold(True)`) is on. A park whose KV the tier
        LRU-evicted replays from scratch instead; an expired budget
        resolves "timeout". Stops at the first no-capacity miss (the
        rest retry next tick). Returns the number resumed."""
        if self._resume_hold or not self._suspended:
            return 0
        resumed = 0
        for rid in sorted(self._suspended):
            outer, meta = self._suspended[rid]
            if outer.done:                     # finished while parked
                self._suspended.pop(rid, None)
                self._suspend_tier.pop(("suspend", rid))
                continue
            dl_s, dl_t, expired = self._remaining_budget(outer)
            if expired:
                self._finish(outer, "timeout")  # drops the park
                continue
            pair = self._suspend_tier.get(("suspend", rid))
            if pair is None:
                # the tier evicted this park to make room for a later
                # one: replay from scratch (at-least-once, never limbo)
                self._suspended.pop(rid, None)
                outer.suspended = False
                self._replay_requeue(outer, "suspend_evicted")
                continue
            snap = dict(meta)
            snap["kv_k"], snap["kv_v"] = pair
            placed = None
            for dst in sorted(self.decode_targets(), key=_Replica.load):
                try:
                    placed = dst.eng.restore_request(
                        snap, deadline_s=dl_s, deadline_ticks=dl_t,
                        _trace=outer.trace)
                except Exception:              # noqa: BLE001
                    placed = None
                if placed is not None:
                    break
            if placed is None:
                break                          # no capacity this tick
            self._suspended.pop(rid, None)
            self._suspend_tier.pop(("suspend", rid))
            outer.suspended = False
            dst.inner[placed.id] = outer
            outer._inner = placed
            outer.replica = dst.idx
            resumed += 1
            if self._admission is not None:
                self._admission._m_res.add()
            if outer.trace is not None:
                outer.trace.instant("resume", replica=dst.idx)
            self._flight.note(router_resume=rid, replica=dst.idx,
                              tick=self._ticks)
        self._m_susp.set(len(self._suspended))
        return resumed

    def _replay_requeue(self, outer: RouterRequest, why: str) -> None:
        """The shared at-least-once fallback: reset the stream (the
        final token list never duplicates), sever the trace subtree,
        requeue at the head of the router queue."""
        outer.tokens.clear()
        outer._inner = None
        outer.replica = None
        outer.suspended = False
        outer.requeues += 1
        self._m_requeue.add()
        if outer.trace is not None:
            outer.trace.sever(why)
            outer.trace.link_replay(cause=why)
        self._pending.appendleft(outer)
        self._m_pending.set(len(self._pending))

    def suspend_lowest_class(self) -> int:
        """Brownout level-2 action: suspend EVERY mid-decode stream of
        the lowest priority class present — but only when more than one
        class is in flight (suspending the only class serves no one).
        Returns the number suspended."""
        infl = self._inflight()
        prios = {int(o.priority) for o in infl}
        if len(prios) < 2:
            return 0
        low = min(prios)
        n = 0
        for outer in [o for o in infl if int(o.priority) == low]:
            if self._suspend(outer):
                n += 1
        return n

    def shed_oldest_pending(self, n: int = 1) -> int:
        """Brownout level-3 action: resolve the `n` oldest router-
        queued requests "evicted" (terminal — the journal and trace
        close, never limbo). Returns the number shed."""
        shed = 0
        while self._pending and shed < n:
            self._finish(self._pending.popleft(), "evicted")
            shed += 1
        self._m_pending.set(len(self._pending))
        return shed

    def set_spec_drafts(self, enabled: bool) -> bool:
        """Broadcast the speculative-drafts toggle to every live
        replica (ServingEngine.set_spec_drafts — a no-op on engines
        built without spec). Returns True when any replica now runs
        drafts."""
        on = False
        for rep in self.live():
            if rep.eng.set_spec_drafts(enabled):
                on = True
        return on

    def set_resume_hold(self, on: bool) -> None:
        """Latch (or release) suspended-stream resumption — the
        brownout level-2 hold: while on, parked streams stay parked
        even when slots free; releasing lets the per-tick resume pass
        drain the parking lot level by level."""
        self._resume_hold = bool(on)

    def _inject_flood(self, n: int) -> None:
        """testing/faults.py `quota_flood@T:N` action: burst `n` small
        priority-(-1) submissions from the "flood" tenant, swallowing
        the quota/backpressure rejects — the drill asserts OTHER
        tenants' admission and latency hold."""
        for _ in range(int(n)):
            try:
                self.submit([1, 2, 3], 4, tenant="flood", priority=-1)
            except (QuotaExceededError, BackpressureError,
                    PoolExhaustedError):
                pass

    def close(self) -> None:
        """Release host-side resources (the journal's WAL handle, the
        step executor). The engines and their device state are
        untouched — close() is for process teardown, not teardown of
        serving."""
        if self._journal is not None:
            self._journal.close()
        if self._exec is not None:
            self._exec.shutdown(wait=False)
            self._exec = None

    # ------------------------------------------------------ terminality
    def _finish(self, req: RouterRequest, reason: str) -> None:
        if req.done:
            return
        req.done = True
        req.finish_reason = reason
        req._inner = None
        if req.suspended:
            # a parked request resolving terminally (timeout / abort /
            # cancel) drops its host-tier KV — never a leak, never limbo
            self._suspended.pop(req.id, None)
            self._suspend_tier.pop(("suspend", req.id))
            req.suspended = False
        if self._journal is not None:
            # the journal's terminal set mirrors THIS seam — exactly
            # once per id per process, and recovery skips already-ended
            # ids, so it stays duplicate-free across a crash
            self._journal.record_terminal(req.id, reason,
                                          tokens=len(req.tokens))
        if req.trace is not None:
            # exactly-once terminal span: an inner engine _finish that
            # already emitted it makes this a no-op (the once-only
            # flag); router-side terminals (requeue-then-abort, cancel
            # while pending) emit here
            req.trace.finish(reason, tokens=len(req.tokens))
        self._m_done.add()

    def cancel(self, req: RouterRequest) -> bool:
        """Resolve `req` with finish_reason "cancelled" right now.
        Returns False when it already resolved."""
        if req.done:
            return False
        if req._inner is not None:
            rep = self.replicas[req.replica]
            rep.inner.pop(req._inner.id, None)
            if rep.alive:
                req._inner.cancel()       # frees the engine slot
        elif not req.suspended:           # parked: _finish drops the KV
            try:
                self._pending.remove(req)
            except ValueError:
                pass
            self._m_pending.set(len(self._pending))
        self._finish(req, "cancelled")
        return True

    def abort_pending(self, reason: str = "evicted") -> int:
        """Resolve EVERY live request (router-queued and on-replica)
        with the terminal `reason` — no request in limbo. Returns the
        number aborted."""
        if reason not in TERMINAL_REASONS:
            raise ValueError(f"reason {reason!r} not in "
                             f"{sorted(TERMINAL_REASONS)}")
        n = 0
        while self._pending:
            self._finish(self._pending.popleft(), reason)
            n += 1
        for rid in list(self._suspended):
            outer, _ = self._suspended[rid]
            if not outer.done:
                self._finish(outer, reason)   # drops the parked KV too
                n += 1
            else:                             # stale park: just drop it
                self._suspended.pop(rid, None)
                self._suspend_tier.pop(("suspend", rid))
        for rep in self.replicas:
            for outer in list(rep.inner.values()):
                if outer.done:
                    continue
                if rep.alive and outer._inner is not None:
                    outer._inner.cancel()
                self._finish(outer, reason)
                n += 1
            rep.inner.clear()
        self._publish_gauges()
        return n

    # ------------------------------------------------- fleet elasticity
    def spawn_replica(self, engine: ServingEngine,
                      role: str = "any") -> int:
        """Scale OUT: add a warm `engine` to the rotation (with a
        disaggregation `role`, default "any") and return its replica
        index. The engine must share params/config with the fleet
        (greedy bit-parity across replicas assumes it); the
        autoscaler's `spawn` factory owns that construction. Joins
        the dispatchable set immediately — the next `step()` places
        queued work on it. Leaves a flight-recorder dump."""
        rep = _Replica(len(self.replicas), engine, role=role)
        self.replicas.append(rep)
        if self._exec is not None:
            # the lazy executor was sized for the OLD fleet — rebuild
            # next tick so every live replica still gets its own worker
            self._exec.shutdown(wait=False)
            self._exec = None
        self._flight.note(router_spawn=rep.idx, role=role,
                          tick=self._ticks,
                          replicas_live=len(self.live()))
        self._flight.dump("router_scale_out")
        self._publish_gauges()
        return rep.idx

    def drain_replica(self, idx: int, migrate: bool = True) -> int:
        """Scale IN, gracefully: replica `idx` stops admitting new
        work but KEEPS STEPPING its in-flight requests; the router
        releases it at the first tick it holds no work. With
        `migrate=True` every snapshot-able in-flight request moves to
        a dispatchable survivor NOW (zero re-prefill, bit-identical
        continuation) so release is typically immediate; requests that
        cannot move (mid-prefill, no capacity) simply finish in place.
        Returns the number migrated. Idempotent; flight-dumps."""
        rep = self.replicas[idx]
        if not rep.alive or rep.draining:
            return 0
        rep.draining = True
        moved = 0
        if migrate:
            for outer in [o for o in rep.inner.values() if not o.done]:
                if self._migrate(outer, rep):
                    moved += 1
        self._flight.note(router_drain=idx, migrated=moved,
                          remaining=len(rep.inner), tick=self._ticks)
        self._flight.dump("router_scale_in")
        self._publish_gauges()
        return moved

    def _release_replica(self, rep: _Replica) -> None:
        """Final step of a graceful drain: the replica holds no work —
        take it out of rotation (NOT a death: nothing requeues, the
        deaths counter stays put)."""
        rep.alive = False
        rep.draining = False
        self._flight.note(router_release=rep.idx, tick=self._ticks)
        self._flight.dump("router_release")

    # ----------------------------------------------------- live migration
    def _migrate(self, outer: RouterRequest, src: _Replica,
                 targets: Optional[List[_Replica]] = None) -> bool:
        """Move `outer` mid-decode from `src` to a dispatchable
        survivor via host KV snapshot — the zero-re-prefill path.
        Order is snapshot -> restore -> detach so any failure leaves
        the source intact (the caller falls back to requeue-replay or
        leaves the request draining in place). Deadlines re-scope to
        the remaining budget; an exhausted budget resolves "timeout"
        here. Returns True only when the request now lives on the
        target replica."""
        inner = outer._inner
        if inner is None or outer.done:
            return False
        try:
            if self._migrate_raise:
                self._migrate_raise = False
                raise RuntimeError("injected migrate_raise")
            snap = src.eng.snapshot_request(inner)
        except Exception:                      # noqa: BLE001 — fault or
            snap = None                        # mid-step corpse: fallback
        if snap is None:
            self._m_mig_fb.add()
            return False
        dl_s, dl_t, expired = self._remaining_budget(outer)
        if expired:
            src.eng.detach_request(inner)
            src.inner.pop(inner.id, None)
            self._finish(outer, "timeout")
            return True                        # resolved, nothing to move
        if targets is None:
            # a migrating request is mid-decode by construction
            # (snapshot_request refuses mid-prefill), so decode-capable
            # replicas come first; prefill-role replicas remain a
            # last-resort landing zone under fleet degradation
            targets = sorted((r for r in self.dispatchable()
                              if r is not src),
                             key=lambda r: (not r.can_decode, r.load()))
        else:
            targets = sorted((r for r in targets if r is not src),
                             key=_Replica.load)
        for dst in targets:
            try:
                new_inner = dst.eng.restore_request(
                    snap, deadline_s=dl_s, deadline_ticks=dl_t,
                    _trace=outer.trace)
            except Exception:                  # noqa: BLE001
                new_inner = None
            if new_inner is None:
                continue
            src.eng.detach_request(inner)
            src.inner.pop(inner.id, None)
            dst.inner[new_inner.id] = outer
            outer._inner = new_inner
            outer.replica = dst.idx
            self._m_mig.add()
            self._mig_bytes += int(snap.get("kv_bytes", 0))
            self._m_mig_bytes.set(self._mig_bytes)
            if outer.trace is not None:
                outer.trace.instant("migrate", src=src.idx, dst=dst.idx,
                                    kv_bytes=int(snap.get("kv_bytes", 0)))
            self._flight.note(router_migration=outer.id, src=src.idx,
                              dst=dst.idx, tick=self._ticks,
                              kv_bytes=int(snap.get("kv_bytes", 0)))
            return True
        self._m_mig_fb.add()                   # snapshot ok, no capacity
        return False

    # ---------------------------------------------------- replica death
    def kill_replica(self, idx: int, reason: str = "killed",
                     migrate: bool = True) -> int:
        """Take replica `idx` out of rotation NOW. Un-terminal requests
        it held migrate to a survivor via live KV snapshot when
        possible (`migrate=True`, zero re-prefill, bit-identical
        continuation); the rest requeue at the HEAD of the router
        queue (they waited longest) and replay from scratch — their
        token lists reset so the final streams carry no duplicates.
        Already-terminal requests stay resolved (exactly-once).
        Returns the number requeued for replay. Idempotent; leaves a
        flight-recorder dump."""
        rep = self.replicas[idx]
        if not rep.alive:
            return 0
        rep.alive = False
        rep.draining = False
        self._m_deaths.add()
        victims = [o for o in rep.inner.values() if not o.done]
        replay = []
        migrated = 0
        for outer in victims:
            # migration-first: reads the dying engine's arrays, which
            # survive `alive=False` (host process, not real hardware
            # loss) — a replica killed because its STEP raised usually
            # fails the snapshot instead and takes the replay path
            if migrate and self._migrate(outer, rep):
                migrated += 1
            elif not outer.done:               # _migrate may resolve it
                replay.append(outer)
        rep.inner.clear()
        for outer in replay:
            outer.tokens.clear()          # replay regenerates the stream
            outer._inner = None
            outer.replica = None
            outer.requeues += 1
            self._m_requeue.add()
            if outer.trace is not None:
                # close the dead replica's span subtree (tagged
                # severed, trace NOT finished) and link the replay
                # attempt — the survivor's spans carry the bumped
                # attempt index
                outer.trace.sever("replica_death", replica=idx)
                outer.trace.link_replay(replica_died=idx)
        self._pending.extendleft(reversed(replay))
        self._flight.note(router_replica_death=idx, reason=reason,
                          migrated=migrated, requeued=len(replay),
                          tick=self._ticks)
        self._flight.dump("router_replica_death")
        if not self.live():
            self.abort_pending("evicted")
        self._publish_gauges()
        return len(replay)

    # ------------------------------------------------------ conveniences
    def drain(self, max_ticks: Optional[int] = None):
        events = []
        ticks = 0
        while self.has_work():
            events.extend(self.step())
            ticks += 1
            if max_ticks is not None and ticks >= max_ticks:
                break
        return events

    def generate(self, prompts: Sequence, max_new_tokens: int,
                 temperature: float = 0.0, top_k: int = 0,
                 eos_id: Optional[int] = None,
                 deadline_s: Optional[float] = None,
                 deadline_ticks: Optional[int] = None,
                 max_ticks: Optional[int] = None) -> List[np.ndarray]:
        """Batch convenience mirroring ServingEngine.generate: submit
        every prompt, drain, resolve stragglers ("evicted" — never
        limbo), return each request's generated ids in order."""
        reqs = [self.submit(p, max_new_tokens, temperature=temperature,
                            top_k=top_k, eos_id=eos_id,
                            deadline_s=deadline_s,
                            deadline_ticks=deadline_ticks)
                for p in prompts]
        self.drain(max_ticks)
        for r in reqs:
            if not r.done:
                self.cancel(r)
                r.finish_reason = "evicted"
        return [np.asarray(r.tokens, np.int32) for r in reqs]


def create_router(params, cfg, replicas: int = 2, family: str = "gpt",
                  max_queue: int = 0, queue_policy: str = "reject",
                  concurrent: bool = True,
                  meshes: Optional[Sequence] = None,
                  tracing: bool = False, clock=None,
                  roles: Optional[Sequence[str]] = None,
                  admission=None, journal_dir: Optional[str] = None,
                  **engine_kw) -> EngineRouter:
    """Build an EngineRouter over `replicas` identical ServingEngines
    sharing ONE param tree (read-only at decode — on a single host the
    replicas share the arrays; in a real deployment each replica's
    params live on its own devices). `meshes` optionally gives each
    replica its own tensor-parallel mesh (inference/serving.py mesh=)
    — the dp(router) x tp(engine) composition. `tracing` turns on
    request-scoped tracing at the ROUTER (the engines inherit the
    trace through dispatch — they need no tracer of their own). A
    `telemetry_jsonl=` engine kwarg fans out per replica
    (`<path>.r<i>`), so each replica streams its own serving_tick
    JSONL — the per-replica files tools/telemetry_report.py's fleet
    mode merges. `roles` (aligned with replica index, values
    any|prefill|decode) turns on prefill/decode disaggregation —
    docs/serving.md §Disaggregation. `admission` (an
    AdmissionController or a {tenant: TenantQuota} dict) turns on
    multi-tenant quotas / weighted-fair dispatch / preempt-to-host;
    `journal_dir` turns on the crash-safe request WAL (construction
    over an existing directory RECOVERS and replays) — docs/serving.md
    §Tenancy, brownout & durability."""
    if replicas < 1:
        raise ValueError(f"replicas must be >= 1; got {replicas}")
    if meshes is not None and len(meshes) != replicas:
        raise ValueError(f"meshes ({len(meshes)}) must match "
                         f"replicas ({replicas})")
    tele = engine_kw.pop("telemetry_jsonl", None)
    engines = [ServingEngine(params, cfg, family=family,
                             mesh=None if meshes is None else meshes[i],
                             telemetry_jsonl=(f"{tele}.r{i}" if tele
                                              else None),
                             **engine_kw)
               for i in range(replicas)]
    return EngineRouter(engines, max_queue=max_queue,
                        queue_policy=queue_policy, concurrent=concurrent,
                        tracing=tracing, clock=clock, roles=roles,
                        admission=admission, journal_dir=journal_dir)
