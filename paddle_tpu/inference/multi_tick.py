"""Fused multi-tick decode: K serving ticks inside ONE jitted lax.scan.

Reference analog: the inference decoder loops of
incubate/nn/layer/fused_transformer.py:1022 dispatch the device once
per generated token — on this host that means paying the ~70-170 ms
tunnel round-trip per token (CLAUDE.md "Environment traps"), and on
any real deployment a dispatch + host-sync tax per token. The repo's
microbenches already amortize dispatch by chaining work inside one jit
(tools/bench_util.py::chained_ms); this module puts the same
amortization in the PRODUCT path: the engine's decode dispatch becomes
a lax.scan of K single-tick bodies, so the engine pays one dispatch +
one host pull per K tokens.

Early exit: the non-spec tick has no host in the loop, so the scan
must decide ON DEVICE when a slot stops emitting. Each step threads an
`alive` mask through the carry and retires a slot when it (a) samples
its request's EOS id, (b) exhausts its max_new_tokens budget, (c)
crosses the engine's max_len position ceiling, or (d) trips the
in-jit isfinite quarantine — exactly the four host-side finish rules
(`ServingEngine._maybe_finish` + the poisoned path), so the device's
per-slot progression is bit-identical to what K separate host-mediated
ticks would have done. Retired rows keep computing (fixed shape) but
their writes route to the frozen position (dense — write-then-attend
masks the garbage exactly like inactive rows) or the scratch page
(paged, `oor_pos`), their columns pad with MT_PAD, and their
positions/gen indices freeze.

The pull grows from [N] to the [N, K] emission matrix (or
[N, K*(gamma+1)] when composed with speculative decode — the scan
body is then spec_decode._spec_core per step): column order is
emission order, MT_PAD (-2, the spec sentinel space: -1 stays the
quarantine verdict) marks "no token", and the host replays the
columns through the same `_emit_token` seam the spec path uses, so
exactly-once terminals, traces, and SLO samples all attribute K
tokens per pull.

Invariants preserved: `sampling` stays the only static flag (<= 2
decode traces — K, gamma, max_len are baked per engine, and the jit
cache key grows the K dim: engines with different K compile distinct
executables); per-slot PRNG streams fold (request id, gen index) per
step exactly like the single-tick path, so sampled streams are
bit-identical; donation and cache pinning are unchanged.

Selection (the kernels/registry.py seam): kernel "multi_tick", impls
"off" | "scan". `PADDLE_TPU_MULTI_TICK` is the env override AND the
kill switch — an off value ("0"/"1"/"off"/"false"/"single") flattens
every engine to single-tick even when built with multi_tick=K, an
integer >= 2 sets K for knob='auto' engines, and unrecognized values
fail safe to off with a stderr warning. Default: off (adoption only
via env > registry — tools/bench_serving.py --multi-tick --adopt is
the evidence-gated writer).
"""
from __future__ import annotations

import os

import jax
import jax.numpy as jnp

from .spec_decode import SPEC_PAD as MT_PAD   # same sentinel space

__all__ = ["MT_PAD", "ENV_MULTI_TICK", "DEFAULT_MULTI_TICK_K",
           "multi_tick_impl", "resolve_multi_tick", "multi_tick_scan",
           "multi_tick_spec_scan"]

ENV_MULTI_TICK = "PADDLE_TPU_MULTI_TICK"

# the K an 'auto' engine gets when the registry (or an un-numbered env
# on value) enables the scan: deep enough to amortize a ~100 ms
# dispatch against ~ms ticks, shallow enough that early-exit waste
# (dead slots riding out the scan) stays small at high occupancy
DEFAULT_MULTI_TICK_K = 4

_OFF_VALUES = frozenset({"0", "1", "off", "false", "no", "single"})
_ON_VALUES = frozenset({"on", "true", "yes", "scan"})


def _env_value():
    """Read + classify PADDLE_TPU_MULTI_TICK: '' (unset), 'off',
    'scan' (enable at the default K), or an int K >= 2. Unrecognized
    values are OFF with a stderr warning — this env var is the kill
    switch, and a typo must fail toward the single-tick shape."""
    env = os.environ.get(ENV_MULTI_TICK, "").strip().lower()
    if not env:
        return ""
    if env in _OFF_VALUES:
        return "off"
    if env in _ON_VALUES:
        return "scan"
    try:
        k = int(env)
    except ValueError:
        k = 0
    if k >= 2:
        return k
    import sys
    print(f"[multi_tick] {ENV_MULTI_TICK}={env!r} is not an int >= 2 "
          f"or one of {sorted(_ON_VALUES)} / {sorted(_OFF_VALUES)}; "
          "treating as 'off' (the kill switch fails safe)",
          file=sys.stderr, flush=True)
    return "off"


def multi_tick_impl():
    """Selector: env PADDLE_TPU_MULTI_TICK > registry winner
    ('multi_tick', current backend class) > 'off'. Returns 'off',
    'scan', or an int K from a numbered env value."""
    env = _env_value()
    if env:
        return env
    from ..kernels import registry
    win = registry.winner("multi_tick",
                          backend=registry.backend_class(
                              jax.default_backend()))
    return win or "off"


def resolve_multi_tick(knob=0) -> int:
    """Engine-build resolution of the multi_tick knob to the effective
    ticks-per-dispatch K (1 = the single-tick shape). knob 0/'auto'
    consults env > registry; an explicit int K >= 1 wins except
    against the env KILL SWITCH (an off value flattens even an
    explicit K — the spec_decode.resolve_spec asymmetry,
    docs/serving.md §Disaggregation)."""
    if knob in (None, "auto"):
        knob = 0
    k = int(knob)
    if k < 0:
        raise ValueError(f"multi_tick must be >= 0 (0 = auto); got {knob}")
    env = _env_value()
    if env == "off":
        return 1
    if k >= 1:
        return k
    if isinstance(env, int):
        return env
    if env == "scan":
        return DEFAULT_MULTI_TICK_K
    from ..kernels import registry
    win = registry.winner("multi_tick",
                          backend=registry.backend_class(
                              jax.default_backend()))
    return DEFAULT_MULTI_TICK_K if win == "scan" else 1


# ---------------------------------------------------------- scan bodies
def multi_tick_scan(params, cache, state, base_key, poison, eos_ids,
                    max_new, *, fwd, cfg, max_top_k, sampling, guard,
                    k_ticks, max_len, oor_pos=None, cache_pin=None,
                    tele=False):
    """K fused non-spec decode ticks (the multi-tick replacement for
    serving._decode_tick — same state tuple / donation / static
    `sampling` flag). `eos_ids` [N] int32 (-1 = no EOS check) and
    `max_new` [N] int32 are the per-slot early-exit inputs the host
    uploads alongside the dirty state rebuild; `max_len` is the baked
    position ceiling. Returns the [N, K] emission matrix (column j =
    the token step j emitted, -1 the quarantine verdict, MT_PAD after
    a slot retires), the updated cache, and the advanced state."""
    from .serving import _pin_cache, _sample, _slot_keys

    toks, positions, active, temps, top_ks, req_ids, gen_idx = state

    def step(carry, _):
        cur, pos, gi, alive, cache = carry
        # retired/inactive rows: frozen position (dense; write-then-
        # attend masks the garbage like single-tick inactive rows) or
        # the scratch page (paged)
        fpos = pos if oor_pos is None else jnp.where(alive, pos, oor_pos)
        logits, cache = fwd(params, cur[:, None], cache, fpos, cfg)
        lg = logits[:, 0].astype(jnp.float32)
        if guard:
            lg = lg * poison[:, None]
        if sampling:
            keys = _slot_keys(base_key, req_ids, gi)
            nxt = _sample(lg, temps, top_ks, keys, max_top_k)
        else:
            nxt = jnp.argmax(lg, axis=-1).astype(jnp.int32)
        nxt = jnp.where(alive, nxt, 0).astype(jnp.int32)
        bad = jnp.zeros_like(alive)
        if guard:
            row_ok = jnp.all(jnp.isfinite(lg), axis=-1)
            bad = alive & ~row_ok
            nxt = jnp.where(bad, -1, nxt)
        col = jnp.where(alive, nxt, MT_PAD)
        inc = alive.astype(jnp.int32)
        pos2, gi2 = pos + inc, gi + inc
        # device-side finish rules, mirroring _maybe_finish + the
        # poisoned path: EOS / length budget / position ceiling /
        # quarantine all retire the row for the rest of the scan
        dead = (bad | ((eos_ids >= 0) & (nxt == eos_ids))
                | (gi2 >= max_new) | (pos2 >= max_len))
        cur2 = jnp.where(alive, nxt, cur)
        if not tele:
            return (cur2, pos2, gi2, alive & ~dead, cache), col
        from ..kernels.decode_attention import attended_tokens
        from ..profiler.serving_telemetry import pack_tick_fields
        trow = pack_tick_fields(
            tokens=jnp.sum(alive & ~bad), active=jnp.sum(alive),
            poisoned=jnp.sum(bad),
            attended=attended_tokens(pos, alive))
        return (cur2, pos2, gi2, alive & ~dead, cache), (col, trow)

    carry0 = (toks, positions, gen_idx, active, cache)
    carry, ys = jax.lax.scan(step, carry0, None, length=k_ticks)
    cur, pos, gi, _alive, cache = carry
    # `active` stays the HOST-owned mask (single-tick contract): the
    # host mirrors the retirements itself via _finish/_clear_slot
    new_state = (cur, pos, active, temps, top_ks, req_ids, gi)
    if not tele:
        return ys.T, _pin_cache(cache, cache_pin), new_state
    cols, trows = ys
    # one TICK_FIELDS row per DISPATCH: counts sum over the K steps;
    # `active` (index 1) reports the slots alive at dispatch start,
    # not slot-steps
    trow = trows.sum(axis=0).at[1].set(trows[0, 1])
    return cols.T, trow, _pin_cache(cache, cache_pin), new_state


def multi_tick_spec_scan(params, cache, state, base_key, poison,
                         draft_poison, eos_ids, max_new, *, fwd, cfg,
                         max_top_k, sampling, guard, gamma, draft_layers,
                         k_ticks, max_len, oor_pos=None, cache_pin=None,
                         tele=False):
    """K fused speculative rounds: lax.scan over spec_decode._spec_core
    with the same alive-mask early exit as multi_tick_scan — a slot
    retires when any token it actually emitted in a block is its EOS,
    when the block's advance exhausts its budget or crosses max_len,
    or when the quarantine flags column 0. Returns the
    [N, K*(gamma+1)] emission matrix (K blocks of gamma+1 columns; a
    retired slot's later blocks are all MT_PAD, which is the host's
    stop marker), the updated cache, and the advanced state."""
    from .serving import _pin_cache
    from .spec_decode import _spec_core

    toks, positions, active, temps, top_ks, req_ids, gen_idx = state
    n = toks.shape[0]
    cols_idx = jnp.arange(gamma + 1, dtype=jnp.int32)[None, :]

    def step(carry, _):
        cur, pos, gi, alive, cache = carry
        emit, cache, new_tok, adv, m = _spec_core(
            params, cache, cur, pos, alive, temps, top_ks, req_ids, gi,
            base_key, poison, draft_poison, fwd=fwd, cfg=cfg,
            max_top_k=max_top_k, sampling=sampling, guard=guard,
            gamma=gamma, draft_layers=draft_layers, oor_pos=oor_pos)
        # dead rows emit a full-PAD block (the core pads cols >= 1 but
        # parks 0 in column 0 for inactive rows; the host needs PAD
        # there to know the slot retired in an earlier block)
        block = jnp.where(alive[:, None], emit, MT_PAD)
        pos2, gi2 = pos + adv, gi + adv
        flagged = alive & (emit[:, 0] < 0)
        emitted = (cols_idx <= m[:, None]) & alive[:, None]
        hit_eos = jnp.any(emitted & (eos_ids[:, None] >= 0)
                          & (emit == eos_ids[:, None]), axis=1)
        dead = (flagged | hit_eos | (gi2 >= max_new)
                | (pos2 >= max_len))
        cur2 = jnp.where(alive, new_tok, cur)
        if not tele:
            return (cur2, pos2, gi2, alive & ~dead, cache), block
        from ..kernels.decode_attention import attended_tokens
        from ..profiler.serving_telemetry import pack_tick_fields
        greedy = (alive & (temps <= 0.0)) if sampling else alive
        trow = pack_tick_fields(
            tokens=jnp.sum(jnp.where(alive & ~flagged, adv, 0)),
            active=jnp.sum(alive),
            poisoned=jnp.sum(flagged),
            attended=attended_tokens(pos, alive),
            spec_proposed=gamma * jnp.sum(greedy),
            spec_accepted=jnp.sum(jnp.where(greedy & ~flagged, m, 0)))
        return (cur2, pos2, gi2, alive & ~dead, cache), (block, trow)

    carry0 = (toks, positions, gen_idx, active, cache)
    carry, ys = jax.lax.scan(step, carry0, None, length=k_ticks)
    cur, pos, gi, _alive, cache = carry
    new_state = (cur, pos, active, temps, top_ks, req_ids, gi)
    if not tele:
        blocks = ys
        emit = jnp.transpose(blocks, (1, 0, 2)).reshape(n, -1)
        return emit, _pin_cache(cache, cache_pin), new_state
    blocks, trows = ys
    emit = jnp.transpose(blocks, (1, 0, 2)).reshape(n, -1)
    trow = trows.sum(axis=0).at[1].set(trows[0, 1])
    return emit, trow, _pin_cache(cache, cache_pin), new_state
