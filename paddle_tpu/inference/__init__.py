"""paddle_tpu.inference — the serving Predictor.

Reference analog: paddle_infer (`AnalysisConfig` analysis_config.cc,
`AnalysisPredictor` inference/api/analysis_predictor.h:94, created via
`create_predictor`): load a saved program + params, run the analysis pass
pipeline, serve named inputs/outputs.

TPU-native collapse: the saved artifact is the jit.save StableHLO module +
weights; "analysis passes" are XLA's compile (fusion/layout happen there),
so Config keeps the knobs that still mean something (model paths, device)
and accepts-and-ignores the GPU/TRT/MKLDNN toggles for port compatibility.
The named-handle API (get_input_handle / copy_from_cpu / run /
copy_to_cpu) matches the reference serving loop shape.
"""
from __future__ import annotations

import os
from typing import Dict, List, Optional

import numpy as np
import jax.numpy as jnp

from ..framework.tensor import Tensor

__all__ = ["Config", "Predictor", "create_predictor", "PrecisionType",
           "ServingEngine", "Request", "create_serving_engine",
           "family_for", "BackpressureError", "PoolExhaustedError",
           "ServingFaultError", "TERMINAL_REASONS",
           "EngineRouter", "RouterRequest", "create_router",
           "AutoscaleConfig", "Autoscaler", "EnginePreemptGuard",
           "AdmissionController", "TenantQuota", "QuotaExceededError",
           "BrownoutConfig", "BrownoutController", "BROWNOUT_LEVELS",
           "RequestJournal"]


class PrecisionType:
    Float32 = "float32"
    Half = "float16"
    Bfloat16 = "bfloat16"
    Int8 = "int8"


class Config:
    """AnalysisConfig analog. `Config(prog_file, params_file)` or
    `Config(model_dir)` with the jit.save prefix inside."""

    def __init__(self, prog_file: Optional[str] = None,
                 params_file: Optional[str] = None):
        if prog_file is not None and prog_file.endswith(".pdmodel"):
            prog_file = prog_file[:-len(".pdmodel")]
        self._prefix = prog_file
        self._params_file = params_file
        self._device = "tpu"
        self._precision = PrecisionType.Float32
        self._enabled = {}

    # ---------------------------------------------------------- ref shape
    def set_model(self, prog_file: str, params_file: Optional[str] = None):
        if prog_file.endswith(".pdmodel"):
            prog_file = prog_file[:-len(".pdmodel")]
        self._prefix = prog_file
        self._params_file = params_file

    def model_dir(self):
        return os.path.dirname(self._prefix or "")

    def prog_file(self):
        return (self._prefix or "") + ".pdmodel"

    def params_file(self):
        return self._params_file or (self._prefix or "") + ".pdiparams"

    def enable_use_gpu(self, memory_pool_init_size_mb=100, device_id=0):
        self._device = "tpu"      # accepted for compat; XLA owns placement

    def disable_gpu(self):
        self._device = "cpu"

    def enable_xpu(self, *a, **k):
        self._device = "tpu"

    def use_gpu(self):
        return False

    def set_precision(self, precision: str):
        """Select the serving precision (PrecisionType.*). The
        Predictor applies it to the loaded params at build — see
        Predictor for the exact semantics per precision."""
        if precision not in (PrecisionType.Float32, PrecisionType.Half,
                             PrecisionType.Bfloat16, PrecisionType.Int8):
            raise ValueError(f"unknown precision {precision!r}")
        self._precision = precision
        return self

    def enable_tensorrt_engine(self, workspace_size=1 << 30,
                               max_batch_size=1, min_subgraph_size=3,
                               precision_mode=None, use_static=False,
                               use_calib_mode=False):
        self._enabled["tensorrt"] = False    # no-op: XLA is the compiler
        # ... but the reference call's precision_mode is the one knob
        # that still means something (the round-5 satellite: _precision
        # was silently ignored)
        if precision_mode is not None:
            self.set_precision(precision_mode)

    def enable_mkldnn(self):
        self._enabled["mkldnn"] = False

    def switch_ir_optim(self, flag=True):
        pass                                  # XLA passes always run

    def enable_memory_optim(self):
        pass

    def set_cpu_math_library_num_threads(self, n):
        pass

    def summary(self) -> str:
        return (f"Config(prefix={self._prefix!r}, device={self._device}, "
                f"precision={self._precision})")


class _IOHandle:
    """Named input/output tensor handle (reference ZeroCopyTensor)."""

    def __init__(self, name: str):
        self.name = name
        self._data: Optional[np.ndarray] = None

    def reshape(self, shape):
        if self._data is None:
            self._data = np.zeros(shape, np.float32)
        else:
            self._data = np.reshape(self._data, shape)

    def copy_from_cpu(self, arr: np.ndarray):
        self._data = np.asarray(arr)

    def copy_to_cpu(self) -> np.ndarray:
        return self._data

    def shape(self):
        return list(self._data.shape) if self._data is not None else []


class Predictor:
    """AnalysisPredictor analog over the jit.save artifact."""

    def __init__(self, config: Config):
        from ..jit import load as jit_load
        self.config = config
        self._layer = jit_load(config._prefix)
        self._apply_precision(config._precision)
        meta = self._layer._meta
        shapes = meta.get("input_shapes", [])
        names = meta.get("input_names") or [f"x{i}"
                                            for i in range(len(shapes))]
        self._in_names = list(names)
        self._inputs: Dict[str, _IOHandle] = {
            n: _IOHandle(n) for n in self._in_names}
        self._out_names: List[str] = []
        self._outputs: Dict[str, _IOHandle] = {}

    def _apply_precision(self, precision: str) -> None:
        """Honor Config._precision on the loaded params. The StableHLO
        artifact pins its compute dtypes at jit.save time, so reduced
        precision lands as a weight ROUND-TRIP on the loaded params:
        the weights carry the reduced-precision values while the
        program keeps its saved dtypes (the trade the reference's fp16
        load makes when the program itself stays fp32).

        - bf16/f16: per-weight dtype round-trip cast.
        - Int8: the WEIGHT-ONLY quantizer — every floating ndim >= 2
          param round-trips through per-output-channel int8
          (quantization.int8.quantize_weight, the reference's
          channel_wise_abs_max). The channel axis follows the
          codebase's own int8-layer conventions: rank-4 conv kernels
          [O, I, kh, kw] quantize per OUTPUT channel (axis 0, the
          Int8Conv2D.from_quanted convention); matmul weights
          [.., K, N] per their LAST axis (Int8Linear). Vectors
          (biases, norms) stay fp. The saved artifact's param list
          carries no names, so unlike the serving engines' named-leaf
          rewrite (quantization/serving.py, which keeps embeddings
          fp) a [V, D] embedding table quantizes like any matrix —
          the documented coarseness of the graph-blind path. A model
          that needs CALIBRATED activation quant should run the
          PTQ/QAT pass + quantization.convert_to_int8 BEFORE
          jit.save — the saved program then already contains real
          int8 dot_generals and loads here under any precision."""
        if precision == PrecisionType.Int8:
            from ..quantization.int8 import _Q, quantize_weight

            def rt(p):
                if (not jnp.issubdtype(p.dtype, jnp.floating)
                        or p.ndim < 2):
                    return p
                w = np.asarray(p, np.float32)
                axis = 0 if w.ndim == 4 else w.ndim - 1
                w_q, scale = quantize_weight(w, channel_axis=axis)
                shape = [1] * w.ndim
                shape[axis] = -1
                return jnp.asarray(
                    w_q.astype(np.float32)
                    * (scale / _Q).reshape(shape), p.dtype)
            self._layer._params = [rt(p) for p in self._layer._params]
        if precision in (PrecisionType.Half, PrecisionType.Bfloat16):
            tgt = (jnp.float16 if precision == PrecisionType.Half
                   else jnp.bfloat16)
            self._layer._params = [
                p.astype(tgt).astype(p.dtype)
                if jnp.issubdtype(p.dtype, jnp.floating) else p
                for p in self._layer._params]

    # ------------------------------------------------------------ ref API
    def get_input_names(self) -> List[str]:
        return list(self._in_names)

    def get_input_handle(self, name: str) -> _IOHandle:
        return self._inputs[name]

    def get_output_names(self) -> List[str]:
        return list(self._out_names)

    def get_output_handle(self, name: str) -> _IOHandle:
        return self._outputs[name]

    def run(self, inputs: Optional[List[np.ndarray]] = None):
        """Execute. Either positional `inputs` (returns arrays, the
        paddle_infer convenience form) or via the named handles. Output
        handles are created once and refilled in place on later runs —
        a serving loop that resolved get_output_handle keeps valid
        handles instead of paying a dict rebuild per call."""
        if inputs is None:
            inputs = [self._inputs[n].copy_to_cpu() for n in self._in_names]
        outs = self._layer(*[jnp.asarray(a) for a in inputs])
        outs = outs if isinstance(outs, list) else [outs]
        arrs = [np.asarray(o._value if isinstance(o, Tensor) else o)
                for o in outs]
        if len(self._out_names) != len(arrs):
            self._out_names = [f"out{i}" for i in range(len(arrs))]
            self._outputs = {n: _IOHandle(n) for n in self._out_names}
        for n, a in zip(self._out_names, arrs):
            self._outputs[n].copy_from_cpu(a)
        return arrs

    def clone(self):
        return Predictor(self.config)


def create_predictor(config: Config) -> Predictor:
    """Reference: paddle_infer.create_predictor."""
    return Predictor(config)


# the continuous-batching serving engine (slot-pool KV cache, bucketed
# prefill, one jitted decode step) — the throughput path the Predictor's
# one-request-per-run loop cannot provide
from .serving import (ServingEngine, Request,          # noqa: E402,F401
                      create_serving_engine, family_for,
                      BackpressureError, PoolExhaustedError,
                      ServingFaultError, TERMINAL_REASONS)
# the replicated-engine router (least-loaded admission, live migration,
# replica-death requeue) — horizontal traffic scaling over N replicas
from .router import (EngineRouter, RouterRequest,      # noqa: E402,F401
                     create_router)
# the serving control loop: SLO/occupancy-driven replica autoscaling
# and tp-preemption tolerance over the router/engine seams above
from .autoscale import (AutoscaleConfig, Autoscaler,   # noqa: E402,F401
                        EnginePreemptGuard)
# overload resilience: multi-tenant admission (quotas / weighted-fair /
# preempt-to-host), the SLO-burn brownout ladder, and the crash-safe
# request journal the router replays after a process death
from .admission import (AdmissionController,           # noqa: E402,F401
                        TenantQuota, QuotaExceededError)
from .brownout import (BrownoutConfig,                 # noqa: E402,F401
                       BrownoutController, BROWNOUT_LEVELS)
from .journal import RequestJournal                    # noqa: E402,F401
