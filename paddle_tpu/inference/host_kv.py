"""Host-tier KV: spill cold prefix pages to host RAM, swap back on hit.

Reference analog: the sharding-stages offload machinery
(distributed/fleet/meta_parallel/sharding/group_sharded_optimizer_stage2.py:322
keeps cold optimizer state on host and round-trips it per step) — the
same device-HBM-is-the-scarce-tier economics applied to the serving
engine's paged KV pool. The device pool's LRU cache (serving._PagePool)
stays the hot tier; this module is the warm tier behind it: when
`alloc()` evicts a REGISTERED page (a prompt-prefix page some future
request could hit), the engine's `on_evict` tap copies the page's K/V
to host ndarrays here before the prefix-map entry drops. Admission's
prefix walk (`_plan_admission`) then consults device first, host
second — a host hit swaps the page back in (one `.at[pid].set` per
page, amortized across the request's lifetime) instead of re-running
prefill over those tokens, so prefix-cache CAPACITY is bounded by host
RAM (this cap), not device HBM.

Correctness leans on the pool's copy-on-write discipline: a REGISTERED
page's content is immutable (writers go through `_ensure_private`
which copies first), so the host copy taken at eviction time is
bit-identical to what a device hit would have read — streams cannot
diverge on tier placement. Eviction from THIS tier (LRU over the byte
cap) is also safe: a dropped key simply re-prefills later, trading
compute for memory, never correctness.

Accounting: `serving_memory_ledger` prices the tier as the
`kv_pool_host` component (host RAM, NOT device HBM — excluded from the
device total); gauges `serving.kv_host_bytes` /
`serving.host_spills` / `serving.host_swapins` ride the telemetry
flush cadence. Kill switch: `PADDLE_TPU_HOST_KV` off values zero the
cap even when the engine was built with host_kv_bytes > 0.
"""
from __future__ import annotations

import collections
import os

import numpy as np

__all__ = ["ENV_HOST_KV", "HostKVTier", "resolve_host_kv"]

ENV_HOST_KV = "PADDLE_TPU_HOST_KV"

_OFF_VALUES = frozenset({"0", "off", "false", "no"})


def resolve_host_kv(knob: int = 0) -> int:
    """Resolve the engine's host_kv_bytes knob to an effective byte
    cap (0 = tier off). The env var kill-switches an explicit cap and
    can set one for knob-0 engines (an int byte count); unrecognized
    values fail safe to OFF with a stderr warning."""
    cap = int(knob or 0)
    if cap < 0:
        raise ValueError(f"host_kv_bytes must be >= 0; got {knob}")
    env = os.environ.get(ENV_HOST_KV, "").strip().lower()
    if not env:
        return cap
    if env in _OFF_VALUES:
        return 0
    try:
        n = int(env)
    except ValueError:
        n = -1
    if n >= 0:
        return n if cap == 0 else cap
    import sys
    print(f"[host_kv] {ENV_HOST_KV}={env!r} is not a byte count or one "
          f"of {sorted(_OFF_VALUES)}; treating as 'off' (the kill "
          "switch fails safe)", file=sys.stderr, flush=True)
    return 0


class HostKVTier:
    """LRU map of prompt-prefix key -> (k, v) host ndarrays (one page
    each, [L, page_size, KV, hd] in the cache dtype). `put` copies (the
    caller may hand a view of a transfer buffer); `get` touches LRU
    order; inserts evict this tier's own LRU entries past `max_bytes`.
    Single-threaded like the engine that owns it."""

    def __init__(self, max_bytes: int):
        self.max_bytes = int(max_bytes)
        self._d: "collections.OrderedDict[object, tuple]" = \
            collections.OrderedDict()
        self.bytes = 0
        self.spills = 0      # pages demoted device -> host (lifetime)
        self.swapins = 0     # pages promoted host -> device (lifetime)
        self.drops = 0       # pages this tier itself evicted (lifetime)

    def __contains__(self, key) -> bool:
        return key in self._d

    def __len__(self) -> int:
        return len(self._d)

    def put(self, key, k_np, v_np) -> bool:
        if key in self._d:
            self._d.move_to_end(key)
            return False
        k_np = np.ascontiguousarray(k_np)
        v_np = np.ascontiguousarray(v_np)
        cost = k_np.nbytes + v_np.nbytes
        if cost > self.max_bytes:
            return False                 # page bigger than the tier
        while self.bytes + cost > self.max_bytes and self._d:
            _, (ek, ev) = self._d.popitem(last=False)    # tier's own LRU
            self.bytes -= ek.nbytes + ev.nbytes
            self.drops += 1
        self._d[key] = (k_np, v_np)
        self.bytes += cost
        self.spills += 1
        return True

    def get(self, key):
        """(k, v) host pair or None; a hit refreshes LRU order. The
        entry STAYS in the tier after a swap-in — registered-page
        content is immutable under COW, so the host copy remains valid
        if the device pool evicts the page again."""
        pair = self._d.get(key)
        if pair is not None:
            self._d.move_to_end(key)
        return pair

    def pop(self, key) -> None:
        pair = self._d.pop(key, None)
        if pair is not None:
            self.bytes -= pair[0].nbytes + pair[1].nbytes

    def clear(self) -> None:
        self._d.clear()
        self.bytes = 0

    def stats(self) -> dict:
        return {"entries": len(self._d), "bytes": self.bytes,
                "spills": self.spills, "swapins": self.swapins,
                "drops": self.drops}
