"""SLO-driven autoscaling + preemption tolerance for the serving fleet.

Reference analog: the elastic fleet manager
(/root/reference/python/paddle/distributed/fleet/elastic/manager.py:124
— etcd leases per worker, watch-for-expiry, scale-out/scale-in
protocol) applied to SERVING: where the reference restarts training
worlds, this module closes the serving control loop ROADMAP item 5
names ("serving-oriented runtime features... heavy traffic from
millions of users"). Two controllers, composable:

- **Autoscaler**: a host-side control loop over `EngineRouter`
  (inference/router.py). Each `tick()` reads ONE occupancy signal —
  (router-queued + in-flight demand) / (dispatchable replicas x
  slots) — plus, when given, the PR-11 `BurnRateMonitor`'s short-
  window burn rate, and drives `spawn_replica` / `drain_replica`
  with the classic control-loop guards: hysteresis (separate
  scale-out/scale-in thresholds with a dead band between), streak
  requirements (`breach_ticks` consecutive breaches before scaling
  out, `idle_ticks` consecutive idles before scaling in), a wall
  cooldown between actions, and hard `min_replicas`/`max_replicas`
  bounds. The clock is injectable, so tests drive whole
  flood->scale-out->idle->scale-in trajectories deterministically.
  Scale-in is GRACEFUL: the drained replica migrates its live
  requests out (zero re-prefill) and the router releases it at the
  first empty tick — no request is ever dropped by a scale decision.

- **EnginePreemptGuard**: the PR-13 lease/watchdog detection
  (parallel/elastic.py `DeviceLeases`) applied to ONE tp-sharded
  ServingEngine's mesh. `poll()` pulses the leases, consults the
  fault hook (`testing/faults.py` ``replica_preempt@T:R`` — R = the
  number of devices to wedge here; the SAME kind names a replica
  index when aimed at the router hook), and on staleness degrades tp
  via `plan_serving_tp`'s shape-aware pricing, rebuilds the engine on
  the surviving mesh (`ServingEngine.rebuild_on_mesh` — sharded-birth
  discipline, live streams migrate through host snapshots in place),
  and resets the leases to the survivors. One pull per tick, the
  trace-count ceilings, and exactly-once terminal resolution all hold
  through the transition (tests/test_autoscale.py asserts each).

Observables: `serving.autoscale.{scale_out,scale_in}` counters +
`serving.autoscale.replicas_target` gauge here (the router adds
`migrations`/`migrate_fallbacks`/`migrated_pages_bytes`), a
flight-recorder dump on every scale/preempt decision, and a
telemetry_report "autoscale" block. docs/serving.md "Autoscaling &
live migration" is the operator story.
"""
from __future__ import annotations

import dataclasses
import time
from typing import Callable, Optional

import numpy as np

from ..profiler import monitor

__all__ = ["AutoscaleConfig", "Autoscaler", "EnginePreemptGuard"]

# testing/faults.py installs a callable here: consulted once per
# EnginePreemptGuard.poll as _FAULT_HOOK(tick) -> dict, e.g.
# {"replica_preempt": n_devices} (wedge the LAST n device leases —
# detection still runs the real staleness rule). None in production.
_FAULT_HOOK = None


@dataclasses.dataclass
class AutoscaleConfig:
    """Control-loop knobs. Occupancy is demand/capacity: (router
    pending + per-replica in-flight) / (dispatchable replicas x
    num_slots) — >= 1.0 means requests are queueing somewhere."""
    min_replicas: int = 1
    max_replicas: int = 4
    scale_out_occupancy: float = 0.95    # breach at/above this...
    scale_in_occupancy: float = 0.25     # ...idle at/below this
    breach_ticks: int = 3                # consecutive breaches -> out
    idle_ticks: int = 8                  # consecutive idles -> in
    cooldown_s: float = 5.0              # min wall gap between actions
    burn_threshold: float = 1.0          # SLO short-window burn -> breach

    def __post_init__(self):
        if not 1 <= self.min_replicas <= self.max_replicas:
            raise ValueError(
                f"need 1 <= min_replicas ({self.min_replicas}) <= "
                f"max_replicas ({self.max_replicas})")
        if self.scale_in_occupancy >= self.scale_out_occupancy:
            raise ValueError(
                "hysteresis requires scale_in_occupancy "
                f"({self.scale_in_occupancy}) < scale_out_occupancy "
                f"({self.scale_out_occupancy})")


class Autoscaler:
    """SLO/occupancy-driven replica-count controller over an
    EngineRouter.

    >>> scaler = Autoscaler(router, spawn=make_engine)
    >>> while router.has_work():
    ...     router.step()
    ...     scaler.tick()

    `spawn` is a zero-arg factory returning a warm ServingEngine
    sharing the fleet's params/config (create_router's engine
    construction is the template). The scaler never blocks a tick:
    spawn cost (engine construction + first-dispatch compiles) is paid
    once per scale-out, and the <5% guardrail-overhead budget
    (tools/bench_serving.py --autoscale-overhead) prices the steady
    state, where `tick()` is pure host arithmetic."""

    def __init__(self, router, spawn: Callable[[], object],
                 cfg: Optional[AutoscaleConfig] = None,
                 slo=None, clock=None):
        self.router = router
        self.spawn = spawn
        self.cfg = cfg or AutoscaleConfig()
        self.slo = slo                  # profiler.slo.BurnRateMonitor
        # default to the ROUTER's clock so one injected clock drives
        # deadlines and autoscale cooldowns coherently
        self._clock = (clock if clock is not None
                       else getattr(router, "_clock", time.perf_counter))
        self._breach = 0                # consecutive breach ticks
        self._idle = 0                  # consecutive idle ticks
        self._last_action = -float("inf")
        self._m_out = monitor.counter("serving.autoscale.scale_out")
        self._m_in = monitor.counter("serving.autoscale.scale_in")
        self._m_target = monitor.gauge(
            "serving.autoscale.replicas_target")
        self._m_occ = monitor.gauge("serving.autoscale.occupancy")
        from ..profiler import flight_recorder
        self._flight = flight_recorder.recorder()
        self._m_target.set(len(router.dispatchable()))

    # ----------------------------------------------------------- signals
    def occupancy(self, role: str = None) -> float:
        """Demand over capacity across the dispatchable fleet; +inf
        when demand exists but nothing admits (all draining/dead) —
        the strongest possible scale-out signal. With a `role`
        ("prefill" | "decode") the signal narrows to that capability
        pool: router-queued requests are demand on the PREFILL pool
        (they are waiting to be prefilled), in-flight load counts
        against whichever pool holds it."""
        reps = self.router.dispatchable()
        if role == "prefill":
            reps = [r for r in reps if r.can_prefill]
        elif role == "decode":
            reps = [r for r in reps if r.can_decode]
        demand = sum(r.load() for r in reps)
        if role != "decode":
            demand += len(self.router._pending)
        cap = sum(r.eng.num_slots for r in reps)
        if cap == 0:
            return float("inf") if demand else 0.0
        return demand / cap

    def burn(self) -> float:
        """Max short-window burn rate across the SLO monitor's
        objectives (0.0 without a monitor — occupancy alone then
        drives the loop)."""
        if self.slo is None:
            return 0.0
        short = min(s for _, s in self.slo.pairs)
        now = self._clock()
        return max((self.slo.burn_rate(o.name, short, now=now)
                    for o in self.slo.objectives), default=0.0)

    # -------------------------------------------------------- the tick
    def tick(self) -> Optional[str]:
        """One control decision. Returns "scale_out" / "scale_in" when
        an action fired, else None. Call once per router step.

        Over a role-split fleet (any replica with role != "any") the
        loop is PER-POOL: scale-out targets the breaching capability
        pool (a prefill flood spawns a prefill replica and leaves the
        decode pool alone — the disaggregation isolation property),
        the spawned replica inherits that role (the `spawn` factory
        may accept a `role=` kwarg; a factory without one still
        works), and scale-in never drains the last replica of a
        capability."""
        cfg = self.cfg
        role_aware = any(r.role != "any" for r in self.router.replicas)
        if role_aware:
            occ_by = {"prefill": self.occupancy("prefill"),
                      "decode": self.occupancy("decode")}
            occ = max(occ_by.values())
        else:
            occ = self.occupancy()
        self._m_occ.set(0.0 if occ == float("inf") else occ)
        breach = (occ >= cfg.scale_out_occupancy
                  or self.burn() >= cfg.burn_threshold)
        idle = (not breach) and occ <= cfg.scale_in_occupancy
        # streaks: the dead band between the thresholds resets BOTH —
        # a noisy signal oscillating inside the band never acts
        self._breach = self._breach + 1 if breach else 0
        self._idle = self._idle + 1 if idle else 0
        now = self._clock()
        if now - self._last_action < cfg.cooldown_s:
            return None
        n = len(self.router.dispatchable())
        if self._breach >= cfg.breach_ticks and n < cfg.max_replicas:
            role = ("any" if not role_aware
                    else max(occ_by, key=occ_by.get))
            idx = self.router.spawn_replica(self._spawn(role), role=role)
            self._after_action(now, occ, n + 1)
            self._m_out.add()
            self._flight.note(autoscale_scale_out=idx, role=role,
                              occupancy=round(min(occ, 1e9), 3),
                              replicas=n + 1)
            self._flight.dump("autoscale_scale_out")
            return "scale_out"
        if self._idle >= cfg.idle_ticks and n > cfg.min_replicas:
            # drain the least-loaded dispatchable replica — its live
            # requests migrate out, the router releases it when empty.
            # Role-split: a replica whose drain would zero out a
            # capability pool is not a candidate
            cands = self.router.dispatchable()
            if role_aware:
                cands = [r for r in cands
                         if not self._last_of_capability(r)]
                if not cands:
                    return None
            victim = min(cands, key=lambda r: (r.load(), -r.idx))
            self.router.drain_replica(victim.idx, migrate=True)
            self._after_action(now, occ, n - 1)
            self._m_in.add()
            self._flight.note(autoscale_scale_in=victim.idx,
                              occupancy=round(occ, 3), replicas=n - 1)
            self._flight.dump("autoscale_scale_in")
            return "scale_in"
        return None

    def _spawn(self, role: str):
        """Call the user's spawn factory, forwarding the target role
        when the factory takes one (a role-oblivious factory — the
        pre-disaggregation signature — still works: every engine is
        role-capable, the role only steers the ROUTER's placement)."""
        if role != "any":
            import inspect
            try:
                params = inspect.signature(self.spawn).parameters
                takes_role = ("role" in params
                              or any(p.kind is p.VAR_KEYWORD
                                     for p in params.values()))
            except (TypeError, ValueError):   # builtins/C callables
                takes_role = False
            if takes_role:
                return self.spawn(role=role)
        return self.spawn()

    def _last_of_capability(self, rep) -> bool:
        """True when draining `rep` would leave the dispatchable set
        without prefill or without decode capability."""
        rest = [r for r in self.router.dispatchable() if r is not rep]
        return (not any(r.can_prefill for r in rest)
                or not any(r.can_decode for r in rest))

    def _after_action(self, now: float, occ: float, target: int) -> None:
        self._last_action = now
        self._breach = 0
        self._idle = 0
        self._m_target.set(target)


class EnginePreemptGuard:
    """Lease/watchdog preemption detection for ONE tp-sharded
    ServingEngine: `poll()` after each engine tick; a stale device
    lease degrades tp through the planner and rebuilds the engine on
    the surviving mesh with its live streams migrated in place.

    >>> guard = EnginePreemptGuard(engine)
    >>> while engine.has_work():
    ...     engine.step()
    ...     guard.poll()

    In production the pulse is fed by per-host heartbeats; in drills
    `testing/faults.py` ``replica_preempt@T:R`` wedges R leases
    through this module's `_FAULT_HOOK` — backdated, so the REAL
    staleness rule fires at the next poll (the elastic-training
    detection discipline, parallel/elastic.py)."""

    def __init__(self, engine, lease_timeout_s: float = 5.0,
                 chip=None):
        if engine.mesh is None:
            raise ValueError("EnginePreemptGuard needs a tp-sharded "
                             "engine (mesh=)")
        from ..parallel.elastic import DeviceLeases
        self.engine = engine
        self.lease_timeout_s = float(lease_timeout_s)
        self.chip = chip
        self._devices = list(np.asarray(engine.mesh.devices).flat)
        self.leases = DeviceLeases(self._devices)
        self._ticks = 0
        self._m_preempt = monitor.counter(
            "serving.autoscale.preemptions")
        from ..profiler import flight_recorder
        self._flight = flight_recorder.recorder()

    def poll(self) -> int:
        """Pulse live leases, detect staleness, degrade+rebuild when
        devices are gone. Returns the NEW tp degree after a rebuild,
        else 0 (no action)."""
        if _FAULT_HOOK is not None:
            actions = _FAULT_HOOK(self._ticks) or {}
            lose = actions.pop("replica_preempt", None)
            if lose:
                from ..parallel.mesh import device_keys
                keys = device_keys(self._devices)
                self.leases.wedge(keys[-int(lose):])
        self._ticks += 1
        self.leases.pulse()
        stale = set(self.leases.stale(self.lease_timeout_s))
        if not stale:
            return 0
        from ..parallel.mesh import build_mesh, device_keys
        keys = device_keys(self._devices)
        survivors = [d for d, k in zip(self._devices, keys)
                     if k not in stale]
        if not survivors:
            raise RuntimeError("every device lease stale — no mesh "
                               "left to rebuild the engine on")
        from ..parallel.planner import plan_serving_tp
        plan = plan_serving_tp(self.engine.cfg, len(survivors),
                               num_slots=self.engine.num_slots,
                               max_len=self.engine.max_len,
                               chip=self.chip)
        tp = plan["tp"]
        mesh = build_mesh({"tp": tp}, devices=survivors[:tp])
        migrated = self.engine.rebuild_on_mesh(mesh)
        self._devices = survivors[:tp]
        self.leases.reset(self._devices)
        self._m_preempt.add()
        self._flight.note(serving_preempt_lost=sorted(stale),
                          new_tp=tp, migrated=migrated,
                          tick=self._ticks)
        self._flight.dump("serving_preempt")
        return tp
