"""Speculative decoding inside the serving tick: self-draft propose +
one-pass verify (Leviathan et al. 2023, "Fast Inference from
Transformers via Speculative Decoding"; Chen et al. 2023,
"Accelerating LLM Decoding with Speculative Sampling").

Reference analog: the inference decoder loops of
incubate/nn/layer/fused_transformer.py:1022 emit ONE token per full
forward — the latency wall PR 4's serving tick inherited. Here each
tick runs a cheap DRAFT pass that proposes `gamma` tokens and ONE
full-depth VERIFY pass that scores all gamma+1 positions, so a tick
emits between 1 and gamma+1 tokens while every emitted token is still
the TARGET model's token (bit-identical greedy streams — the property
every kernel in this repo ships behind).

Self-draft (the default and only built-in draft): the first
`draft_layers` layers of the existing stacked lax.scan, sharing the
target's params AND its KV cache/pages — the stacked-params layout
makes truncated depth a static slice (`forward_cached(...,
layers=K)`), and the draft needs no cache of its own because the
verify pass rewrites every drafted position at full depth anyway. The
draft's working cache is a throwaway first-K-layers view, discarded at
the end of the tick (a separate small draft model would need its own
prefill/cache lifecycle; the seam is `draft_layers` — depth IS the
draft-quality knob here).

The whole propose+verify runs as ONE jitted tick (`spec_tick`) with
the same state tuple, donation, and trace ceiling as the non-spec
`_decode_tick`, preserving the PR 4-6 invariants:

- ONE host pull per tick — the pull is the [N, gamma+1] emission
  matrix instead of an [N] vector; column 0 is always a real token
  (or the -1 quarantine sentinel), accepted tokens follow, and PAD
  (-2) fills the rest, so the host derives the per-slot acceptance
  count with no extra download.
- zero recompiles after warmup — gamma/draft_layers are baked per
  engine; `sampling` stays the only static flag (<= 2 traces).
- exactly-once — host bookkeeping mirrors the device advance
  (positions += accepted+1) and the quarantine/finish paths reuse the
  non-spec seams unchanged.

Correctness of greedy acceptance (why emitted streams are
bit-identical to non-spec decode): the verify pass writes K/V for all
gamma+1 positions BEFORE attending (kernels/decode_attention.py write-
then-attend order), and the position mask admits cache slots <= the
query's own position only, so verify row i sees exactly the cache the
incremental path would have — including nothing of rows > i. Every
emitted token is `argmax` of a verify row whose input prefix matched
the true stream, i.e. exactly the token the one-token-per-tick path
would have produced. Rejected rows' K/V is stale garbage past the new
position: masked until the next tick's writes overwrite it in order
(dense), or rolled back page-by-page by the engine (paged — see
ServingEngine._rollback_spec_pages).

Mixed spec/non-spec batches: sampled slots (temperature > 0) ride the
SAME tick — their token samples from verify row 0 (the exact logits
the non-spec tick computes, under the same fold_in PRNG stream) and
their acceptance is forced to 0, so greedy slots speculate while
sampled slots advance one reproducible token. Rejection-sampled
multi-token speculation for temperature > 0 is deliberately out of
scope: greedy acceptance is exact and bit-verifiable; a sampled
acceptance rule would change sampled streams vs the non-spec engine.

Draft-failure degradation: a non-finite draft logit row forces that
slot's acceptance to 0 — the slot degrades to non-spec decode for the
tick (verify row 0 is still the target's own healthy logits). Only
TARGET-model non-finite logits quarantine (the -1 sentinel), and only
over rows the slot actually emits. `testing/faults.py draft_nan`
injects the draft lane; tools/chaos_serving.py asserts the degrade.

Selection (the kernels/registry.py seam, same precedence story as
decode_attention): kernel "spec_decode", impls "off" | "spec".
`PADDLE_TPU_SPEC_DECODE` is the env override AND the kill switch —
an explicit off value ("0"/"off"/"dense"/"false") disables
speculation even on engines built with spec_decode="spec", so a
misbehaving deployment can be flattened without a code change.
Default: off (adoption only via env > sweep-winner > registry —
tools/bench_serving.py --spec --adopt is the evidence-gated writer).
"""
from __future__ import annotations

import os

import jax
import jax.numpy as jnp

__all__ = ["SPEC_PAD", "spec_decode_impl", "resolve_spec", "spec_tick"]

ENV_SPEC_DECODE = "PADDLE_TPU_SPEC_DECODE"

# emission-matrix pad sentinel: -1 is the quarantine verdict, real ids
# are never negative — -2 marks "no token emitted in this column"
SPEC_PAD = -2

_OFF_VALUES = frozenset({"0", "off", "dense", "false", "no"})
_ON_VALUES = frozenset({"1", "spec", "on", "true", "yes"})


def _env_value() -> str:
    """Read + classify PADDLE_TPU_SPEC_DECODE: '' (unset), 'off',
    or 'spec'. An unrecognized value is treated as OFF with a stderr
    warning — this env var is the kill switch, and a typo that
    silently ENABLED speculation would do the exact opposite of what
    the operator reached for."""
    env = os.environ.get(ENV_SPEC_DECODE, "").strip().lower()
    if not env:
        return ""
    if env in _ON_VALUES:
        return "spec"
    if env not in _OFF_VALUES:
        import sys
        print(f"[spec_decode] {ENV_SPEC_DECODE}={env!r} is not one of "
              f"{sorted(_ON_VALUES)} / {sorted(_OFF_VALUES)}; treating "
              "as 'off' (the kill switch fails safe)",
              file=sys.stderr, flush=True)
    return "off"


def spec_decode_impl() -> str:
    """Selector: env PADDLE_TPU_SPEC_DECODE > registry winner
    ('spec_decode', current backend class) > 'off'. The env var is
    re-read per engine build like the Pallas kill switches."""
    env = _env_value()
    if env:
        return env
    from ..kernels import registry
    win = registry.winner("spec_decode",
                          backend=registry.backend_class(
                              jax.default_backend()))
    return win or "off"


def resolve_spec(knob: str) -> bool:
    """Engine-build resolution of the spec_decode knob ('auto' | 'off'
    | 'spec') against the selector. The env KILL SWITCH is absolute: an
    off value disables speculation even for knob='spec' (the only
    selector in the repo where env beats an explicit caller choice —
    that asymmetry is what makes it a kill switch, docs/serving.md).
    Unrecognized env values count as off (_env_value fails safe)."""
    if _env_value() == "off":
        return False
    if knob == "off":
        return False
    if knob == "spec":
        return True
    if knob == "auto":
        return spec_decode_impl() == "spec"
    raise ValueError(f"spec_decode {knob!r} (auto|off|spec)")


def _spec_core(params, cache, toks, positions, active, temps, top_ks,
               req_ids, gen_idx, base_key, poison, draft_poison, *,
               fwd, cfg, max_top_k, sampling, guard, gamma, draft_layers,
               oor_pos=None):
    """One propose+verify round over explicit per-slot arrays — the
    body `spec_tick` wraps for the single-dispatch path and
    inference/multi_tick.py scans K times with an early-exit alive
    mask threaded through `active`. Returns (emit [N, gamma+1], cache,
    new_tok [N], adv [N], m [N]): the emission matrix, the rewritten
    cache, the last accepted token, the per-slot position/gen advance
    (m + 1 for active rows, 0 otherwise), and the raw acceptance
    count."""
    from .serving import _sample, _slot_keys
    from ..models.decode import greedy_accept

    n = toks.shape[0]

    # ---- draft: gamma greedy steps through the first draft_layers
    # layers on a THROWAWAY view of the cache (the verify pass is the
    # only authoritative writer; the view exists so draft step i+1 can
    # attend draft step i's K/V within this tick)
    dcache = {"k": cache["k"][:draft_layers],
              "v": cache["v"][:draft_layers]}
    if "pt" in cache:
        dcache["pt"] = cache["pt"]
    d_tok = toks
    draft_cols = []
    draft_ok = jnp.ones((n,), bool)
    for i in range(gamma):
        dpos = positions + i
        fpos = (dpos if oor_pos is None
                else jnp.where(active, dpos, oor_pos))
        lg_d, dcache = fwd(params, d_tok[:, None], dcache, fpos, cfg,
                           layers=draft_layers)
        row = lg_d[:, 0].astype(jnp.float32) * draft_poison[:, None]
        draft_ok &= jnp.all(jnp.isfinite(row), axis=-1)
        d_tok = jnp.argmax(row, axis=-1).astype(jnp.int32)
        draft_cols.append(d_tok)
    del dcache                                # discarded by design
    draft = jnp.stack(draft_cols, axis=1)     # [N, gamma]

    # ---- verify: ONE full-depth pass over [cur, d1..dgamma]; its
    # writes land at positions pos..pos+gamma through the same
    # write-then-attend seam as prefill, so row i attends exactly the
    # incremental path's cache (the position mask zeroes rows > i)
    vt = jnp.concatenate([toks[:, None], draft], axis=1)
    fpos = (positions if oor_pos is None
            else jnp.where(active, positions, oor_pos))
    logits, cache = fwd(params, vt, cache, fpos, cfg)
    lg = logits.astype(jnp.float32)           # [N, gamma+1, V]
    if guard:
        lg = lg * poison[:, None, None]
    tgt = jnp.argmax(lg, axis=-1).astype(jnp.int32)   # [N, gamma+1]

    # ---- acceptance: leading drafts matching the target's argmax;
    # a poisoned draft degrades to 0 (non-spec for this tick)
    m = greedy_accept(draft, tgt)
    m = jnp.where(draft_ok, m, 0)
    if sampling:
        # sampled slots take verify row 0 — the exact logits (and the
        # exact fold_in key stream) of the non-spec tick — and never
        # accept drafts, so their streams stay bit-identical
        keys = _slot_keys(base_key, req_ids, gen_idx)
        first = _sample(lg[:, 0], temps, top_ks, keys, max_top_k)
        m = jnp.where(temps > 0.0, 0, m)
        emit0 = jnp.where(temps > 0.0, first, tgt[:, 0]).astype(jnp.int32)
    else:
        emit0 = tgt[:, 0]
    cols = jnp.arange(gamma + 1, dtype=jnp.int32)[None, :]
    emit = jnp.where(cols <= m[:, None], tgt, SPEC_PAD)
    emit = emit.at[:, 0].set(jnp.where(active, emit0, 0))
    emit = jnp.where(active[:, None] | (cols == 0), emit, SPEC_PAD)
    if guard:
        # quarantine ONLY over rows the slot emits: rejected drafts'
        # rows may hold garbage-token logits and must not evict
        row_ok = jnp.all(jnp.isfinite(lg), axis=-1)   # [N, gamma+1]
        bad = jnp.any(~row_ok & (cols <= m[:, None]), axis=1)
        emit = emit.at[:, 0].set(
            jnp.where(active & bad, -1, emit[:, 0]))

    adv = jnp.where(active, m + 1, 0).astype(jnp.int32)
    last = jnp.take_along_axis(emit, m[:, None], axis=1)[:, 0]
    new_tok = jnp.where(active, last, toks).astype(jnp.int32)
    return emit, cache, new_tok, adv, m


def spec_tick(params, cache, state, base_key, poison, draft_poison, *,
              fwd, cfg, max_top_k, sampling, guard, gamma, draft_layers,
              oor_pos=None, cache_pin=None, tele=False):
    """THE speculative mixed step (the spec-mode replacement for
    serving._decode_tick, same state tuple / donation / static
    `sampling` flag). Per active slot: gamma truncated-depth draft
    steps propose tokens, one full-depth verify pass scores all
    gamma+1 positions, and the greedy acceptance rule
    (models/decode.greedy_accept) picks how many to emit. Returns the
    [N, gamma+1] emission matrix (column 0 = the always-emitted token
    or the -1 quarantine sentinel; SPEC_PAD beyond the accepted
    prefix), the updated cache, and the advanced state. The math
    lives in `_spec_core` so the fused multi-tick scan
    (inference/multi_tick.py) can run the same round K times per
    dispatch with an early-exit mask.

    `draft_poison` [N] is the draft-lane fault multiplier (all-ones in
    production; testing.faults draft_nan sets one lane to nan INSIDE
    the jit): a non-finite draft row forces acceptance 0 — the slot
    degrades to non-spec decode, never quarantine, because verify row
    0 is the target's own logits. `poison` is the TARGET lane, handled
    exactly as in the non-spec tick.

    Tensor-parallel serving (ServingEngine mesh=): the draft's
    first-K-layers throwaway cache view inherits the pool's head
    sharding (a leading-axis slice never moves the KV-head axis), the
    verify pass writes through the same sharded seam, and `cache_pin`
    pins the returned pool leaves to their input NamedShardings
    exactly like the non-spec tick (serving._pin_cache) — donation
    aliases, zero recompiles, still one [N, gamma+1] pull per mesh."""
    from .serving import _pin_cache

    toks, positions, active, temps, top_ks, req_ids, gen_idx = state
    emit, cache, new_tok, adv, m = _spec_core(
        params, cache, toks, positions, active, temps, top_ks, req_ids,
        gen_idx, base_key, poison, draft_poison, fwd=fwd, cfg=cfg,
        max_top_k=max_top_k, sampling=sampling, guard=guard, gamma=gamma,
        draft_layers=draft_layers, oor_pos=oor_pos)
    new_state = (new_tok, positions + adv, active, temps, top_ks,
                 req_ids, gen_idx + adv)
    if not tele:
        return emit, _pin_cache(cache, cache_pin), new_state
    # in-tick telemetry row riding the emission-matrix pull (zero extra
    # transfers — profiler/serving_telemetry). DEVICE-side truth: a
    # mid-block host finish may drop tail tokens from the stream, but
    # the device did the work these fields price. Proposed counts
    # greedy slots only (sampled slots never speculate — same rule as
    # the host acceptance ledger); accepted sums the kept drafts.
    from ..kernels.decode_attention import attended_tokens
    from ..profiler.serving_telemetry import pack_tick_fields
    flagged = active & (emit[:, 0] < 0)
    greedy = (active & (temps <= 0.0)) if sampling else active
    trow = pack_tick_fields(
        tokens=jnp.sum(jnp.where(active & ~flagged, adv, 0)),
        active=jnp.sum(active),
        poisoned=jnp.sum(flagged),
        attended=attended_tokens(positions, active),
        spec_proposed=gamma * jnp.sum(greedy),
        spec_accepted=jnp.sum(jnp.where(greedy & ~flagged, m, 0)))
    return emit, trow, _pin_cache(cache, cache_pin), new_state
