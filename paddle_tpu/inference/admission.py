"""Multi-tenant admission control: per-tenant token-bucket quotas,
priority classes, and weighted-fair dispatch ordering for the serving
router.

Reference analog: the fleet's job-queue admission discipline
(/root/reference/python/paddle/distributed/fleet/elastic/manager.py:124
gates world membership on leases and quotas before work schedules)
applied to serving REQUESTS: where the reference admits workers into a
training world, this module admits requests into the router's dispatch
rotation — and the overload response is graceful (rate-limit, reorder,
preempt-to-host) instead of the single shed_oldest knob.

Three mechanisms, all host-side arithmetic (zero device work, zero
extra pulls — the <5% steady-state budget of
tools/bench_serving.py --admission-overhead):

- **Token-bucket quotas** (`TenantQuota.tokens_per_s` / `burst`): each
  submit charges its worst-case token cost (prompt + max_new_tokens).
  An empty bucket raises the typed `QuotaExceededError` carrying the
  exact `retry_after_s` refill wait — clients back off with arithmetic
  instead of guessing. rate <= 0 means unmetered (the default tenant).

- **Weighted-fair ordering** (`order()`): the router's pending queue
  dispatches by (priority DESC, tenant virtual-time ASC) — stride
  scheduling, each tenant's virtual time advancing by charged tokens
  over its weight, so a flooding tenant's backlog cannot starve a
  light tenant at EQUAL priority, and priority classes strictly
  dominate fairness (an SLO-critical tenant jumps any backlog).

- **Priority bookkeeping for preemption**: `preempt_candidate()` picks
  the lowest-priority mid-decode victim strictly below an arriving
  request's class — the router SUSPENDS it (PR-17 `snapshot_request`
  parks its KV in a PR-19 `HostKVTier`) rather than evicting, and it
  resumes later with zero re-prefilled tokens.

The controller is deliberately router-agnostic (it never touches
replicas or engines): the router asks three questions — may this
admit? in what order? who yields? — and owns every state transition,
so exactly-once terminal resolution stays in ONE place
(inference/router.py `_finish`).

Observables: per-tenant serving.admission.{admitted,rejected,
suspended}.<tenant> counters plus serving.admission.{preemptions,
resumes} — telemetry_report's "admission" block; clock injectable so
tests drive refill trajectories deterministically.
"""
from __future__ import annotations

import dataclasses
import time
from typing import Dict, Optional

from ..profiler import monitor

__all__ = ["TenantQuota", "QuotaExceededError", "AdmissionController"]


class QuotaExceededError(RuntimeError):
    """A tenant's token bucket cannot cover the request's worst-case
    token cost. `retry_after_s` is the exact refill wait until THIS
    request would admit — the client-visible backoff budget."""

    def __init__(self, msg: str, tenant: str = "",
                 retry_after_s: float = 0.0, tokens_requested: int = 0,
                 tokens_available: float = 0.0):
        super().__init__(msg)
        self.tenant = tenant
        self.retry_after_s = float(retry_after_s)
        self.tokens_requested = int(tokens_requested)
        self.tokens_available = float(tokens_available)


@dataclasses.dataclass
class TenantQuota:
    """One tenant's admission envelope. `tokens_per_s <= 0` = no rate
    limit (the bucket never empties); `burst` caps the bucket (how much
    a quiet tenant can bank); `weight` scales fair-share dispatch (a
    weight-2 tenant drains its backlog twice as fast as a weight-1 one
    at equal priority)."""
    tokens_per_s: float = 0.0
    burst: float = 0.0
    weight: float = 1.0

    def __post_init__(self):
        if self.tokens_per_s > 0 and self.burst <= 0:
            raise ValueError(
                f"a rate-limited tenant needs burst > 0; got "
                f"tokens_per_s={self.tokens_per_s}, burst={self.burst}")
        if self.weight <= 0:
            raise ValueError(f"weight must be > 0; got {self.weight}")


class _Bucket:
    __slots__ = ("level", "last", "vtime")

    def __init__(self, burst: float, now: float):
        self.level = float(burst)   # tokens available
        self.last = now             # last refill timestamp
        self.vtime = 0.0            # stride-scheduling virtual time


class AdmissionController:
    """Quota + fairness + preemption policy for EngineRouter. Tenants
    not named in `quotas` get `default` (unmetered, weight 1 unless
    overridden). Single-threaded with the router that owns it."""

    def __init__(self, quotas: Optional[Dict[str, TenantQuota]] = None,
                 default: Optional[TenantQuota] = None, clock=None):
        self.quotas = dict(quotas or {})
        self.default = default or TenantQuota()
        self._clock = clock if clock is not None else time.perf_counter
        self._b: Dict[str, _Bucket] = {}
        self._m_pre = monitor.counter("serving.admission.preemptions")
        self._m_res = monitor.counter("serving.admission.resumes")
        self._per: Dict[tuple, object] = {}

    # --------------------------------------------------------- plumbing
    def quota(self, tenant: str) -> TenantQuota:
        return self.quotas.get(tenant, self.default)

    def _bucket(self, tenant: str) -> _Bucket:
        b = self._b.get(tenant)
        if b is None:
            b = self._b[tenant] = _Bucket(self.quota(tenant).burst,
                                          self._clock())
        return b

    def counter(self, kind: str, tenant: str):
        """Lazily-minted per-tenant counter
        (serving.admission.<kind>.<tenant> — the dynamic-suffix family
        telemetry_report's admission block groups)."""
        key = (kind, tenant)
        c = self._per.get(key)
        if c is None:
            c = self._per[key] = monitor.counter(
                f"serving.admission.{kind}.{tenant}")
        return c

    # ------------------------------------------------------------ quota
    def charge(self, tenant: str, tokens: int) -> None:
        """Deduct `tokens` from the tenant's bucket, refilled to now.
        Raises QuotaExceededError (with the exact retry-after) when the
        bucket cannot cover it — nothing is deducted then, so a
        rejected request never burns budget."""
        q = self.quota(tenant)
        if q.tokens_per_s <= 0:
            return
        b = self._bucket(tenant)
        now = self._clock()
        b.level = min(q.burst, b.level + (now - b.last) * q.tokens_per_s)
        b.last = now
        if tokens > b.level:
            retry = (tokens - b.level) / q.tokens_per_s
            raise QuotaExceededError(
                f"tenant {tenant!r} quota exceeded: request costs "
                f"{tokens} tokens, {b.level:.1f} available "
                f"(rate {q.tokens_per_s}/s); retry in {retry:.2f}s",
                tenant=tenant, retry_after_s=retry,
                tokens_requested=tokens, tokens_available=b.level)
        b.level -= tokens

    # --------------------------------------------------------- fairness
    def note_dispatch(self, tenant: str, tokens: int) -> None:
        """Advance the tenant's virtual time by its served work over
        its weight — the stride-scheduling update `order()` reads."""
        self._bucket(tenant).vtime += tokens / self.quota(tenant).weight

    def order(self, pending) -> list:
        """The weighted-fair dispatch order over router-pending
        requests: priority classes strictly first (higher number =
        more urgent), then each tenant's virtual time (least-served
        first), then submission id (FIFO within a tenant). Pure
        reorder — no request is dropped or charged here."""
        return sorted(
            pending,
            key=lambda r: (-int(getattr(r, "priority", 0)),
                           self._bucket(getattr(r, "tenant",
                                                "default")).vtime,
                           r.id))

    # ------------------------------------------------------- preemption
    def preempt_candidate(self, inflight, priority: int):
        """The suspension victim for an arriving `priority`-class
        request: the LOWEST-priority mid-decode request STRICTLY below
        it (ties broken toward the most recently submitted — it has
        the least sunk work to park). None when nothing yields —
        preemption never inverts or equalizes priorities."""
        cands = [r for r in inflight
                 if not r.done and int(getattr(r, "priority", 0))
                 < int(priority)]
        if not cands:
            return None
        return min(cands, key=lambda r: (int(getattr(r, "priority", 0)),
                                         -r.id))

    def stats(self) -> dict:
        now = self._clock()
        out = {}
        for t, b in self._b.items():
            q = self.quota(t)
            level = (b.level if q.tokens_per_s <= 0 else
                     min(q.burst, b.level + (now - b.last)
                         * q.tokens_per_s))
            out[t] = {"tokens_available": round(level, 1),
                      "vtime": round(b.vtime, 3),
                      "weight": q.weight,
                      "tokens_per_s": q.tokens_per_s}
        return out
