"""Brownout ladder: ordered, observable service degradation under
sustained SLO breach, with level-by-level auto-recovery.

Reference analog: the elastic fleet manager's staged scale response
(/root/reference/python/paddle/distributed/fleet/elastic/manager.py:124
— watch a health signal, act with hysteresis, recover when it clears)
composed with the PR-11 SLO burn-rate monitor (profiler/slo.py): where
the autoscaler (inference/autoscale.py) answers sustained overload by
ADDING capacity, this controller answers it by SHEDDING work quality —
the two compose (brownout buys time while a spawn warms), and both run
the same control-loop guards: breach/clear streaks, a wall cooldown
between transitions, an injectable clock.

The ladder (each level includes the ones below it):

    level  name                  action (enter)               undo (exit)
    -----  --------------------  ---------------------------  -----------
    0      normal                —                            —
    1      no_spec_drafts        disable speculative decode   re-enable
           (cheapest: drafts burn FLOPs for latency; greedy
           streams are bit-identical either way, so nothing
           user-visible changes but capacity frees)
    2      suspend_low_priority  suspend the lowest priority  resume
           class's mid-decode streams to host KV (PR-17
           snapshot -> PR-19 host tier; zero re-prefill on
           resume) and hold resumption
    3      shed_oldest           actively shed the oldest
           router-queued requests, `shed_per_tick` per tick
           (terminal "evicted" — never limbo)

Escalation: `breach_ticks` consecutive ticks with any objective's
short-window burn rate >= `burn_threshold` (the PR-11 fast-burn
signal) steps ONE level up; recovery: `recover_ticks` consecutive
clear ticks steps ONE level down — degradation is gradual both ways,
and the `cooldown_s` wall gap between transitions stops flapping.

Observables: the serving.brownout_level gauge (telemetry_report's
"admission" block), serving.brownout.{escalations,recoveries,shed}
counters, a flight-recorder dump per transition (brownout_escalate /
brownout_recover with the level, burn rate and tick).
tools/chaos_serving.py brownout_ladder drives a full
breach -> 3 -> clear -> 0 trajectory on an injected clock.
"""
from __future__ import annotations

import dataclasses
import time
from typing import Optional

from ..profiler import monitor

__all__ = ["BrownoutConfig", "BrownoutController", "BROWNOUT_LEVELS"]

BROWNOUT_LEVELS = ("normal", "no_spec_drafts", "suspend_low_priority",
                   "shed_oldest")


@dataclasses.dataclass
class BrownoutConfig:
    """Control-loop knobs (autoscale.AutoscaleConfig's discipline)."""
    burn_threshold: float = 1.0     # short-window burn >= this = breach
    breach_ticks: int = 3           # consecutive breaches -> step up
    recover_ticks: int = 8          # consecutive clears -> step down
    cooldown_s: float = 5.0         # min wall gap between transitions
    shed_per_tick: int = 2          # level-3 shedding rate
    max_level: int = 3              # ladder ceiling (<= len(LEVELS)-1)

    def __post_init__(self):
        if not 0 <= self.max_level < len(BROWNOUT_LEVELS):
            raise ValueError(
                f"max_level must be in 0..{len(BROWNOUT_LEVELS) - 1}; "
                f"got {self.max_level}")
        if self.breach_ticks < 1 or self.recover_ticks < 1:
            raise ValueError("breach_ticks and recover_ticks must be "
                             ">= 1")
        if self.shed_per_tick < 1:
            raise ValueError(f"shed_per_tick must be >= 1; "
                             f"got {self.shed_per_tick}")


class BrownoutController:
    """SLO-burn-driven degrade controller over an EngineRouter.

    >>> ctrl = BrownoutController(router, slo=burn_monitor)
    >>> while router.has_work():
    ...     router.step()
    ...     ctrl.tick()

    `slo` is a profiler.slo.BurnRateMonitor (the caller feeds it
    latency samples); without one the controller never escalates —
    brownout is an SLO response, not a load response (the autoscaler
    owns occupancy)."""

    def __init__(self, router, slo=None,
                 cfg: Optional[BrownoutConfig] = None, clock=None):
        self.router = router
        self.slo = slo
        self.cfg = cfg or BrownoutConfig()
        self._clock = (clock if clock is not None
                       else getattr(router, "_clock", time.perf_counter))
        self.level = 0
        self._breach = 0
        self._clear = 0
        self._last_action = -float("inf")
        self._m_level = monitor.gauge("serving.brownout_level")
        self._m_esc = monitor.counter("serving.brownout.escalations")
        self._m_rec = monitor.counter("serving.brownout.recoveries")
        self._m_shed = monitor.counter("serving.brownout.shed")
        from ..profiler import flight_recorder
        self._flight = flight_recorder.recorder()
        self._m_level.set(0)

    # ----------------------------------------------------------- signal
    def burn(self) -> float:
        """Max short-window burn rate across the monitor's objectives
        (0.0 without a monitor)."""
        if self.slo is None:
            return 0.0
        short = min(s for _, s in self.slo.pairs)
        now = self._clock()
        return max((self.slo.burn_rate(o.name, short, now=now)
                    for o in self.slo.objectives), default=0.0)

    # ------------------------------------------------------------- tick
    def tick(self) -> Optional[str]:
        """One control decision after a router step. Returns
        "escalate" / "recover" when the level moved, else None. While
        AT level >= 3, sheds `shed_per_tick` oldest queued requests
        every tick regardless of transitions."""
        cfg = self.cfg
        burn = self.burn()
        breach = burn >= cfg.burn_threshold
        self._breach = self._breach + 1 if breach else 0
        self._clear = self._clear + 1 if not breach else 0
        moved = None
        now = self._clock()
        if now - self._last_action >= cfg.cooldown_s:
            if (breach and self._breach >= cfg.breach_ticks
                    and self.level < cfg.max_level):
                self._apply(self.level + 1, burn, now)
                moved = "escalate"
            elif (not breach and self._clear >= cfg.recover_ticks
                    and self.level > 0):
                self._apply(self.level - 1, burn, now)
                moved = "recover"
        if self.level >= 3:
            shed = self.router.shed_oldest_pending(cfg.shed_per_tick)
            if shed:
                self._m_shed.add(shed)
        return moved

    def _apply(self, new: int, burn: float, now: float) -> None:
        """Run the enter/exit actions between the current level and
        `new` (always one step with the default tick logic, but written
        transitional so a forced multi-level jump stays correct)."""
        old = self.level
        step = 1 if new > old else -1
        lvl = old
        while lvl != new:
            nxt = lvl + step
            if step > 0:
                self._enter(nxt)
            else:
                self._exit(lvl)
            lvl = nxt
        self.level = new
        self._breach = 0
        self._clear = 0
        self._last_action = now
        self._m_level.set(new)
        (self._m_esc if step > 0 else self._m_rec).add()
        self._flight.note(
            brownout_level=new, previous=old,
            name=BROWNOUT_LEVELS[new], burn=round(burn, 3),
            tick=getattr(self.router, "_ticks", -1))
        self._flight.dump("brownout_escalate" if step > 0
                          else "brownout_recover")

    def _enter(self, lvl: int) -> None:
        r = self.router
        if lvl == 1:
            r.set_spec_drafts(False)
        elif lvl == 2:
            r.set_resume_hold(True)       # suspended streams stay parked
            r.suspend_lowest_class()
        # lvl 3 needs no one-shot action: tick() sheds while AT it

    def _exit(self, lvl: int) -> None:
        r = self.router
        if lvl == 1:
            r.set_spec_drafts(True)       # no-op on spec-less engines
        elif lvl == 2:
            r.set_resume_hold(False)      # step() resumes as slots free
