"""Crash-safe request journal: an append-only WAL of admission and
terminal events, so a serving process killed mid-stream can restart and
replay every accepted-but-unresolved request.

Reference analog: the layered crash/resume protocol of
/root/reference/python/paddle/fluid/incubate/checkpoint/auto_checkpoint.py:72
(TrainEpochRange — persist "where was I" markers keyed by job id,
resume from the last COMPLETE record) applied to SERVING requests
instead of training epochs, with the durability discipline of
parallel/checkpoint.py (write + flush + fsync, CRC32 per record, the
commit marker IS the integrity check).

Record format — one line per event, append-only:

    <crc32:08x> <json>\n

where the CRC covers the json payload bytes. Two event kinds:

- ``admit``: the request's full replay envelope (id, tenant, priority,
  prompt ids, max_new_tokens, temperature, top_k, eos_id) — written
  AFTER validation + quota pass, fsynced BEFORE submit() returns, so
  "accepted" means "durable".
- ``end``: (id, finish_reason, tokens delivered) — written by the
  router's exactly-once terminal seam (`EngineRouter._finish`), so the
  journal's terminal set mirrors the in-process terminal set. A
  quota/backpressure REJECT writes an ``end`` with no ``admit`` (the
  satellite-1 contract: every rejection leaves a journal terminal
  event); recovery ignores end-only ids — a rejection was client-
  visible as an exception and must not replay.

Recovery semantics (`recover()`, run at construction): read the WAL
front-to-back, stop at the FIRST record that fails CRC or JSON — a
torn tail (the process died mid-append) is TOLERATED, never fatal: the
half-written record's request never saw submit() return, so dropping
it is correct. Every ``admit`` with no ``end`` is un-terminal and
returned via `replayable()`; the router re-submits them (at-least-once
prefill — the crash lost the KV — with exactly-once terminal
resolution under the SAME request id, so the journal's terminal set
stays duplicate-free across the crash). Deadlines are deliberately NOT
journaled: wall budgets from a dead process are meaningless after
restart, so replayed requests run un-deadlined.

Observables: serving.journal.appends / replays / recovered / torn
counters (telemetry_report's "admission" block). Fault drill:
testing/faults.py ``journal_torn@N`` truncates N bytes off the WAL
tail through this module's `_FAULT_HOOK` before recovery reads it —
the torn-tail path exercised on demand (tools/chaos_serving.py
process_crash_replay covers the real SIGKILL).
"""
from __future__ import annotations

import json
import os
import zlib
from typing import Dict, List, Optional

from ..profiler import monitor

__all__ = ["RequestJournal", "WAL_NAME"]

WAL_NAME = "requests.wal"

# testing/faults.py installs a callable here: consulted ONCE per
# recovery as _FAULT_HOOK() -> dict, e.g. {"journal_torn": nbytes}
# (truncate the WAL tail by nbytes before reading — the torn-tail
# drill). None in production.
_FAULT_HOOK = None


def _fsync_dir(path: str) -> None:
    # parallel/checkpoint.py:_fsync_dir — the rename/append becomes
    # durable only when the DIRECTORY entry is too
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


class RequestJournal:
    """Append-only request WAL under `journal_dir` (one file,
    `requests.wal`). Single-writer, same-thread as the router that owns
    it. Construction RECOVERS: reads the existing WAL (tolerating a
    torn tail), indexes admits/ends, and reopens the file for append —
    new records land after whatever survived."""

    def __init__(self, journal_dir: str, fsync: bool = True):
        self.dir = str(journal_dir)
        self.path = os.path.join(self.dir, WAL_NAME)
        self.fsync = bool(fsync)
        self.admits: Dict[int, dict] = {}
        self.ends: Dict[int, str] = {}
        self.torn_bytes = 0
        self._m_app = monitor.counter("serving.journal.appends")
        self._m_rec = monitor.counter("serving.journal.recovered")
        self._m_torn = monitor.counter("serving.journal.torn")
        os.makedirs(self.dir, exist_ok=True)
        self._recover()
        self._f = open(self.path, "ab")
        _fsync_dir(self.dir)

    # ---------------------------------------------------------- recovery
    def _recover(self) -> None:
        if _FAULT_HOOK is not None:
            actions = _FAULT_HOOK() or {}
            tear = int(actions.pop("journal_torn", 0) or 0)
            if tear > 0 and os.path.exists(self.path):
                size = os.path.getsize(self.path)
                os.truncate(self.path, max(size - tear, 0))
        if not os.path.exists(self.path):
            return
        with open(self.path, "rb") as f:
            data = f.read()
        good = 0                       # bytes of intact prefix
        for line in data.split(b"\n"):
            if not line:
                good += 1              # the separator itself
                continue
            rec = self._parse(line)
            if rec is None:
                break                  # torn tail: stop, never raise
            good += len(line) + 1
            if rec["ev"] == "admit":
                self.admits[int(rec["id"])] = rec
            elif rec["ev"] == "end":
                self.ends[int(rec["id"])] = str(rec.get("reason", ""))
        good = min(good, len(data))
        if good < len(data):
            # the torn record's request never saw submit() return —
            # truncating to the intact prefix is correct AND keeps
            # later appends from landing mid-garbage
            self.torn_bytes = len(data) - good
            os.truncate(self.path, good)
            self._m_torn.add()
        if self.admits:
            self._m_rec.add(len(self.admits))

    @staticmethod
    def _parse(line: bytes) -> Optional[dict]:
        try:
            crc_hex, payload = line.split(b" ", 1)
            if int(crc_hex, 16) != (zlib.crc32(payload) & 0xFFFFFFFF):
                return None
            rec = json.loads(payload)
        except Exception:                          # noqa: BLE001
            return None
        return rec if isinstance(rec, dict) and "ev" in rec else None

    def replayable(self) -> List[dict]:
        """Admit records with no terminal event, id order — what the
        crashed process accepted but never resolved. End-only ids
        (rejections) never appear here by construction."""
        return [self.admits[i] for i in sorted(self.admits)
                if i not in self.ends]

    @property
    def next_id(self) -> int:
        """1 + the largest id the WAL has seen — the router seeds its
        id counter here so replayed and fresh requests never collide
        (the journal's terminal set stays keyed uniquely)."""
        ids = list(self.admits) + list(self.ends)
        return max(ids) + 1 if ids else 0

    # ------------------------------------------------------------ append
    def _append(self, rec: dict) -> None:
        payload = json.dumps(rec, separators=(",", ":")).encode()
        crc = zlib.crc32(payload) & 0xFFFFFFFF
        self._f.write(b"%08x " % crc + payload + b"\n")
        self._f.flush()
        if self.fsync:
            os.fsync(self._f.fileno())
        self._m_app.add()

    def record_admit(self, req_id: int, prompt, max_new_tokens: int,
                     temperature: float, top_k: int, eos_id,
                     tenant: str, priority: int) -> None:
        """The durable-admission record — fsynced before submit()
        returns, so every request the caller believes accepted survives
        a SIGKILL."""
        self._append({"ev": "admit", "id": int(req_id),
                      "tenant": str(tenant), "priority": int(priority),
                      "prompt": [int(t) for t in prompt],
                      "max_new_tokens": int(max_new_tokens),
                      "temperature": float(temperature),
                      "top_k": int(top_k),
                      "eos_id": None if eos_id is None else int(eos_id)})
        # mirror the on-disk index so a SAME-PROCESS re-recover (tests)
        # and replayable() agree with what a restart would see
        self.admits[int(req_id)] = {
            "ev": "admit", "id": int(req_id), "tenant": str(tenant),
            "priority": int(priority),
            "prompt": [int(t) for t in prompt],
            "max_new_tokens": int(max_new_tokens),
            "temperature": float(temperature), "top_k": int(top_k),
            "eos_id": None if eos_id is None else int(eos_id)}

    def record_terminal(self, req_id: int, reason: str,
                        tokens: int = 0) -> None:
        """The terminal record — written from the router's exactly-once
        `_finish`, so at most one per id per process; across a crash,
        recovery skips already-ended ids, keeping the terminal set
        duplicate-free."""
        self._append({"ev": "end", "id": int(req_id),
                      "reason": str(reason), "tokens": int(tokens)})
        self.ends[int(req_id)] = str(reason)

    def close(self) -> None:
        try:
            self._f.close()
        except Exception:                          # noqa: BLE001
            pass
