"""Continuous-batching serving engine: slot-pool KV cache, bucketed
prefill, one jitted decode step.

Reference analog: the dedicated serving runtime — AnalysisPredictor
(inference/api/analysis_predictor.h:94) driving the
FusedMultiTransformer decode loops
(incubate/nn/layer/fused_transformer.py:1022) — generalized to
iteration-level scheduling (cf. Orca's continuous batching, OSDI '22,
and vLLM's paged KV cache, SOSP '23): requests join and leave the
running batch between decode steps instead of start-and-finish
together.

TPU-native design (everything jit-shaped, nothing dynamic on device):

- **Slot pool.** A fixed pool of N decode slots backed by one donated
  stacked KV cache ({"k","v"} buffers of [L, N, max_len, KV, hd] — the
  k/v pair realizes the single [L, 2, N, ...] buffer of the design
  with per-leaf donation, so XLA aliases both across ticks and the
  cache never leaves the device). All writes are in-place
  `dynamic_update_slice`es (kernels/decode_attention.write_kv).
- **One jitted mixed decode step.** Every tick advances ALL slots one
  token under per-slot position/active masks: the per-row-position
  `forward_cached` (models/gpt.py, models/llama.py) runs the N tokens
  as one batch, and greedy + temperature/top-k sampling happens inside
  the jit (per-request PRNG streams derived by folding the request id
  and token index into the engine key, so sampled streams are
  reproducible regardless of slot placement or batch composition).
  The tick's signature is shape-stable -> one trace per sampling mode
  (greedy-only ticks skip the sampling machinery via a static flag)
  for the engine's lifetime.
- **Bucketed prefill.** Prompts pad to the power-of-two bucket
  (models/decode.prompt_bucket — the same policy as the bucketed
  greedy driver, which is what makes engine token streams
  bit-identical to per-request `greedy_generate`); the true length and
  target slot ride through the trace as scalars, so any prompt length
  hits one of ~log(max_len) compiled executables.
- **Python-side scheduler.** Admission queue, slot allocation,
  EOS/max-token/cache-full eviction, and mid-decode join of new
  requests into freed slots all happen on the host between ticks; the
  device only ever sees the fixed-shape tick.

Stale cache contents (a freed slot's previous request, bucket-pad
garbage) are never attended: the decode-attention mask admits cache
slots <= the query's own position only, and decode writes overwrite
the pad region in order (kernels/decode_attention.py).

Observability: serving.* monitor counters/gauges (slot occupancy,
queue depth, tokens emitted, prefills, decode ticks) and
RecordEvent spans around every prefill/decode tick —
tools/telemetry_report.py summarizes them, tools/bench_serving.py
measures the engine against sequential per-request decode.
"""
from __future__ import annotations

import collections
import dataclasses
import functools
from typing import Callable, List, Optional, Sequence

import numpy as np
import jax
import jax.numpy as jnp

from ..models.decode import prompt_bucket
from ..profiler import RecordEvent, monitor

__all__ = ["ServingEngine", "Request", "ModelFamily", "family_for",
           "create_serving_engine"]


# --------------------------------------------------------------- families
@dataclasses.dataclass(frozen=True)
class ModelFamily:
    """The seam a model family exposes to the engine: a cached forward
    that accepts per-row positions (slot-indexed writes) and a cache
    factory. Both flagship decoders qualify; any future family that
    implements the same contract plugs in here."""
    name: str
    forward_cached: Callable    # (params, tokens[B,T], cache, pos, cfg)
    init_cache: Callable        # (cfg, batch, max_len) -> {"k","v"}


def family_for(name: str) -> ModelFamily:
    if name == "gpt":
        from ..models import gpt
        return ModelFamily("gpt", gpt.gpt_forward_cached,
                           gpt.init_kv_cache)
    if name == "llama":
        from ..models import llama
        return ModelFamily("llama", llama.llama_forward_cached,
                           llama.init_kv_cache)
    raise ValueError(f"unknown model family {name!r} (gpt|llama)")


# --------------------------------------------------------------- requests
class Request:
    """One generation request riding through the engine."""

    __slots__ = ("id", "prompt", "max_new_tokens", "temperature",
                 "top_k", "eos_id", "tokens", "done", "finish_reason",
                 "slot")

    def __init__(self, req_id, prompt, max_new_tokens, temperature,
                 top_k, eos_id):
        self.id = req_id
        self.prompt = prompt
        self.max_new_tokens = max_new_tokens
        self.temperature = temperature
        self.top_k = top_k
        self.eos_id = eos_id
        self.tokens: List[int] = []     # generated ids, in order
        self.done = False
        self.finish_reason: Optional[str] = None
        self.slot: Optional[int] = None

    def __repr__(self):
        return (f"Request(id={self.id}, len={len(self.prompt)}, "
                f"gen={len(self.tokens)}/{self.max_new_tokens}, "
                f"done={self.done})")


# ------------------------------------------------------- in-jit sampling
def _slot_keys(base_key, req_ids, gen_idx):
    """Per-slot PRNG keys: fold (request id, token index) into the
    engine key — streams depend on the request, never on slot placement
    or batch composition."""
    def one(rid, gi):
        return jax.random.fold_in(jax.random.fold_in(base_key, rid), gi)
    return jax.vmap(one)(req_ids, gen_idx)


def _sample(lg, temps, top_ks, keys, max_top_k: int):
    """lg [N,V] f32 -> next token [N] int32. Greedy where temp <= 0
    (bit-identical to the greedy driver's argmax); otherwise
    temperature softmax sampling, truncated to the request's top_k
    (<= the engine's static max_top_k) when top_k > 0."""
    greedy = jnp.argmax(lg, axis=-1)
    safe_t = jnp.maximum(temps, 1e-6)[:, None]
    full = jax.vmap(jax.random.categorical)(keys, lg / safe_t)
    sampled = full
    if max_top_k > 0:
        vals, idx = jax.lax.top_k(lg, max_top_k)           # [N,K]
        k_eff = jnp.minimum(jnp.where(top_ks <= 0, max_top_k, top_ks),
                            max_top_k)
        masked = jnp.where(jnp.arange(max_top_k)[None, :] < k_eff[:, None],
                           vals, -jnp.inf)
        choice = jax.vmap(jax.random.categorical)(keys, masked / safe_t)
        trunc = jnp.take_along_axis(idx, choice[:, None], axis=1)[:, 0]
        sampled = jnp.where(top_ks > 0, trunc, full)
    return jnp.where(temps <= 0.0, greedy, sampled).astype(jnp.int32)


# --------------------------------------------------------- jitted bodies
# slot-state tuple riding through the decode tick (all [N], device-
# resident and DONATED alongside the cache — the host only downloads
# the sampled tokens, one small pull per tick)
#   (cur_tok, positions, active, temps, top_ks, req_ids, gen_idx)
def _decode_tick(params, cache, state, base_key, *, fwd, cfg, max_top_k,
                 sampling):
    """THE mixed step: all N slots advance one token. Each slot's
    current token is written at its own position; sampling runs in-jit;
    inactive slots compute too (fixed shape) but their output is masked
    and their slot region is overwritten at the next prefill.
    `sampling` is STATIC: greedy-only ticks skip the key-fold +
    categorical machinery entirely (~0.4 ms/tick on the CPU rung), so
    the tick has at most two traces for the engine's lifetime."""
    toks, positions, active, temps, top_ks, req_ids, gen_idx = state
    logits, cache = fwd(params, toks[:, None], cache, positions, cfg)
    lg = logits[:, 0].astype(jnp.float32)
    if sampling:
        keys = _slot_keys(base_key, req_ids, gen_idx)
        nxt = _sample(lg, temps, top_ks, keys, max_top_k)
    else:
        nxt = jnp.argmax(lg, axis=-1).astype(jnp.int32)
    nxt = jnp.where(active, nxt, 0).astype(jnp.int32)
    inc = active.astype(jnp.int32)
    state = (nxt, positions + inc, active, temps, top_ks, req_ids,
             gen_idx + inc)
    return nxt, cache, state


def _prefill_slot(params, cache, padded, true_len, slot, temps, top_ks,
                  req_ids, base_key, *, fwd, init_cache, cfg, max_top_k,
                  sampling):
    """Bucketed prefill of ONE request into slot `slot`: run the padded
    prompt through a fresh single-row BUCKET-length cache (bit-identical
    K/V and logits to the greedy driver's full-length prefill — the
    masked softmax gives padded/absent positions an exact 0), sample the
    first token from the last REAL position's logits, and write the row
    into the pool, wiping the slot's previous occupant up to the bucket
    (anything staler is masked until decode overwrites it). Trace key:
    the bucket length only (true_len/slot are traced scalars)."""
    mini = init_cache(cfg, 1, padded.shape[1])
    logits, mini = fwd(params, padded, mini, 0, cfg)
    last = jax.lax.dynamic_slice_in_dim(
        logits, true_len - 1, 1, axis=1)[:, 0].astype(jnp.float32)
    if sampling:
        keys = _slot_keys(base_key, req_ids, jnp.zeros((1,), jnp.int32))
        first = _sample(last, temps, top_ks, keys, max_top_k)[0]
    else:
        first = jnp.argmax(last, axis=-1).astype(jnp.int32)[0]
    cache = {
        "k": jax.lax.dynamic_update_slice(
            cache["k"], mini["k"], (0, slot, 0, 0, 0)),
        "v": jax.lax.dynamic_update_slice(
            cache["v"], mini["v"], (0, slot, 0, 0, 0)),
    }
    return first, cache


# ----------------------------------------------------------- the engine
class ServingEngine:
    """Iteration-level scheduler over a fixed slot pool.

    >>> eng = ServingEngine(params, cfg, family="gpt", num_slots=8)
    >>> req = eng.submit(prompt_ids, max_new_tokens=32)
    >>> while eng.has_work():
    ...     for r, tok in eng.step():   # (request, token) emissions
    ...         ...
    >>> req.tokens

    `generate(prompts, ...)` wraps submit+drain for batch use.
    """

    def __init__(self, params, cfg, family="gpt", num_slots: int = 8,
                 max_len: Optional[int] = None, max_top_k: int = 0,
                 seed: int = 0, bucket_lo: int = 8,
                 decode_unroll: int = 0):
        self.family = (family_for(family) if isinstance(family, str)
                       else family)
        self.cfg = cfg
        self.num_slots = int(num_slots)
        self.max_len = int(max_len or cfg.max_seq_len)
        if self.max_len > getattr(cfg, "max_seq_len", self.max_len):
            # positions past the table (gpt wpe / llama rope cache) would
            # CLAMP, silently corrupting every later token
            raise ValueError(
                f"max_len ({self.max_len}) exceeds the model's "
                f"max_seq_len ({cfg.max_seq_len}): position embeddings "
                "beyond the table would clamp, not error")
        self.max_top_k = int(max_top_k)
        self.bucket_lo = int(bucket_lo)
        self._params = params
        self._cache = self.family.init_cache(cfg, self.num_slots,
                                             self.max_len)
        self._base_key = jax.random.PRNGKey(seed)

        # at T=1 the layer scan's cache slice/restack dominates the
        # matvecs: fully unroll shallow stacks (bit-identical numerics —
        # models/gpt.py decode_scan_unroll). 0 = auto, 1 = keep the scan.
        # Auto only applies when the config still carries the field's
        # default (1): an explicitly tuned cfg.decode_scan_unroll wins.
        cfg_unroll = getattr(cfg, "decode_scan_unroll", None)
        if decode_unroll == 0:
            if cfg_unroll not in (None, 1):
                decode_unroll = cfg_unroll
            else:
                layers = getattr(cfg, "num_layers", 0)
                decode_unroll = layers if 0 < layers <= 8 else 1
        run_cfg = cfg
        if cfg_unroll not in (None, decode_unroll):
            try:
                run_cfg = dataclasses.replace(
                    cfg, decode_scan_unroll=decode_unroll)
            except TypeError:        # non-dataclass custom family config
                run_cfg = cfg

        n = self.num_slots
        # host MIRRORS of the slot state (scheduling reads these); the
        # device copies ride donated through the tick and are rebuilt
        # from the mirrors only when admission/eviction dirties them
        self._positions = np.zeros(n, np.int32)   # tokens in each slot
        self._active = np.zeros(n, bool)
        self._cur_tok = np.zeros(n, np.int32)     # last sampled token
        self._temps = np.zeros(n, np.float32)
        self._top_ks = np.zeros(n, np.int32)
        self._req_ids = np.zeros(n, np.int32)
        self._gen_idx = np.zeros(n, np.int32)     # next sample index
        self._dstate = None                       # device state tuple
        self._dirty = True
        self._slot_req: List[Optional[Request]] = [None] * n
        self._queue: collections.deque = collections.deque()
        self._next_id = 0

        self._decode = jax.jit(
            functools.partial(_decode_tick, fwd=self.family.forward_cached,
                              cfg=run_cfg, max_top_k=self.max_top_k),
            donate_argnums=(1, 2), static_argnames=("sampling",))
        self._prefill = jax.jit(
            functools.partial(_prefill_slot,
                              fwd=self.family.forward_cached,
                              init_cache=self.family.init_cache,
                              cfg=run_cfg, max_top_k=self.max_top_k),
            donate_argnums=(1,), static_argnames=("sampling",))

        self._m_occ = monitor.gauge("serving.slot_occupancy")
        self._m_queue = monitor.gauge("serving.queue_depth")
        self._m_tok = monitor.counter("serving.tokens_emitted")
        self._m_pre = monitor.counter("serving.prefills")
        self._m_tick = monitor.counter("serving.decode_ticks")
        self._m_sub = monitor.counter("serving.requests_submitted")
        self._m_done = monitor.counter("serving.requests_completed")

    # ------------------------------------------------------- observables
    def trace_counts(self):
        """(decode traces, prefill traces) — the zero-recompile
        acceptance observable: decode holds at one trace per sampling
        mode (<= 2 forever); prefill grows only with NEW (prompt
        bucket, sampling mode) pairs — ceiling 2·log2(max_len)."""
        return self._decode._cache_size(), self._prefill._cache_size()

    def has_work(self) -> bool:
        return bool(self._queue) or bool(self._active.any())

    @property
    def active_requests(self):
        return [r for r in self._slot_req if r is not None]

    # --------------------------------------------------------- admission
    def submit(self, prompt, max_new_tokens: int, temperature: float = 0.0,
               top_k: int = 0, eos_id: Optional[int] = None) -> Request:
        """Queue one request. prompt: 1-D int token ids. Returns the
        live Request; its .tokens fills in as the engine steps."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        t0 = prompt.shape[0]
        if t0 < 1:
            raise ValueError("empty prompt")
        if max_new_tokens < 1:
            raise ValueError(f"max_new_tokens must be >= 1; "
                             f"got {max_new_tokens}")
        if t0 + max_new_tokens > self.max_len:
            raise ValueError(
                f"prompt ({t0}) + max_new_tokens ({max_new_tokens}) "
                f"exceeds the engine's max_len ({self.max_len})")
        if top_k > 0 and self.max_top_k <= 0:
            raise ValueError(
                "engine was built with max_top_k=0 (greedy/temperature "
                "only); rebuild with max_top_k >= the largest top_k "
                "you will request")
        if top_k > self.max_top_k:
            raise ValueError(f"top_k={top_k} exceeds the engine's "
                             f"static max_top_k={self.max_top_k}")
        req = Request(self._next_id, prompt, int(max_new_tokens),
                      float(temperature), int(top_k), eos_id)
        self._next_id += 1
        self._queue.append(req)
        self._m_sub.add()
        self._m_queue.set(len(self._queue))
        return req

    # --------------------------------------------------------- the tick
    def step(self):
        """One engine tick: admit queued requests into free slots
        (one bucketed prefill each), then advance all active slots one
        token through the single jitted decode step. Returns this
        tick's (request, token) emissions in slot order."""
        events: List[tuple] = []
        while self._queue:
            slot = self._free_slot()
            if slot is None:
                break
            self._admit(slot, self._queue.popleft(), events)

        if self._active.any():
            if self._dirty:
                self._dstate = (
                    jnp.asarray(self._cur_tok), jnp.asarray(self._positions),
                    jnp.asarray(self._active), jnp.asarray(self._temps),
                    jnp.asarray(self._top_ks), jnp.asarray(self._req_ids),
                    jnp.asarray(self._gen_idx))
                self._dirty = False
            sampling = bool(np.any(self._temps[self._active] > 0.0))
            with RecordEvent("serving.decode_tick"):
                nxt, self._cache, self._dstate = self._decode(
                    self._params, self._cache, self._dstate,
                    self._base_key, sampling=sampling)
                toks = np.asarray(nxt)     # ONE host pull per tick
            self._m_tick.add()
            for i in np.nonzero(self._active)[0]:
                req = self._slot_req[i]
                tok = int(toks[i])
                # mirror exactly what the tick did on device (positions
                # and gen_idx advanced under the active mask) — no
                # download, and the device state stays clean unless an
                # eviction below dirties it
                self._positions[i] += 1
                self._cur_tok[i] = tok
                self._gen_idx[i] += 1
                req.tokens.append(tok)
                events.append((req, tok))
                self._m_tok.add()
                self._maybe_finish(i, req)

        self._m_occ.set(int(self._active.sum()))
        self._m_queue.set(len(self._queue))
        return events

    def drain(self, max_ticks: Optional[int] = None):
        """Step until idle (or max_ticks); returns all emissions."""
        events = []
        ticks = 0
        while self.has_work():
            events.extend(self.step())
            ticks += 1
            if max_ticks is not None and ticks >= max_ticks:
                break
        return events

    def generate(self, prompts: Sequence, max_new_tokens: int,
                 temperature: float = 0.0, top_k: int = 0,
                 eos_id: Optional[int] = None) -> List[np.ndarray]:
        """Batch convenience: submit every prompt, drain, return each
        request's generated ids (submission order)."""
        reqs = [self.submit(p, max_new_tokens, temperature=temperature,
                            top_k=top_k, eos_id=eos_id) for p in prompts]
        self.drain()
        return [np.asarray(r.tokens, np.int32) for r in reqs]

    # ---------------------------------------------------------- plumbing
    def _free_slot(self) -> Optional[int]:
        for i in range(self.num_slots):
            if self._slot_req[i] is None:
                return i
        return None

    def _admit(self, slot: int, req: Request, events: list) -> None:
        t0 = len(req.prompt)
        tb = prompt_bucket(t0, self.max_len, self.bucket_lo)
        padded = np.zeros((1, tb), np.int32)
        padded[0, :t0] = req.prompt
        with RecordEvent("serving.prefill"):
            first, self._cache = self._prefill(
                self._params, self._cache, jnp.asarray(padded),
                jnp.asarray(t0, jnp.int32), jnp.asarray(slot, jnp.int32),
                jnp.asarray([req.temperature], jnp.float32),
                jnp.asarray([req.top_k], jnp.int32),
                jnp.asarray([req.id], jnp.int32), self._base_key,
                sampling=req.temperature > 0.0)
            tok = int(first)               # first generated token
        self._m_pre.add()
        req.slot = slot
        self._slot_req[slot] = req
        self._positions[slot] = t0
        self._active[slot] = True
        self._cur_tok[slot] = tok
        self._temps[slot] = req.temperature
        self._top_ks[slot] = req.top_k
        self._req_ids[slot] = req.id
        self._gen_idx[slot] = 1
        self._dirty = True
        req.tokens.append(tok)
        events.append((req, tok))
        self._m_tok.add()
        self._maybe_finish(slot, req)

    def _maybe_finish(self, slot: int, req: Request) -> None:
        reason = None
        if req.eos_id is not None and req.tokens[-1] == req.eos_id:
            reason = "eos"
        elif len(req.tokens) >= req.max_new_tokens:
            reason = "length"
        elif self._positions[slot] >= self.max_len:
            reason = "cache_full"      # unreachable via submit's check
        if reason is None:
            return
        req.done = True
        req.finish_reason = reason
        req.slot = None
        self._slot_req[slot] = None
        self._active[slot] = False
        self._positions[slot] = 0
        self._cur_tok[slot] = 0
        self._temps[slot] = 0.0
        self._top_ks[slot] = 0
        self._gen_idx[slot] = 0
        self._dirty = True
        self._m_done.add()


def create_serving_engine(model_or_params, cfg=None, **kw) -> ServingEngine:
    """Build a ServingEngine from a facade model (GPTModel/LlamaModel —
    family and params are inferred) or from a raw (params, cfg) pair
    plus family=..."""
    from ..models.facade import FacadeModel
    if isinstance(model_or_params, FacadeModel):
        model = model_or_params
        family = kw.pop("family", getattr(model, "_serving_family", None))
        if family is None:
            raise ValueError(f"{type(model).__name__} does not name a "
                             "_serving_family; pass family=...")
        from ..framework.dispatch import raw_value
        params = {n: raw_value(p) for n, p in model._params.items()}
        return ServingEngine(params, model.cfg, family=family, **kw)
    if cfg is None:
        raise ValueError("create_serving_engine(params, cfg, ...) needs "
                         "the model config")
    return ServingEngine(model_or_params, cfg, **kw)
