"""Continuous-batching serving engine: slot-pool KV cache, bucketed
prefill, one jitted decode step.

Reference analog: the dedicated serving runtime — AnalysisPredictor
(inference/api/analysis_predictor.h:94) driving the
FusedMultiTransformer decode loops
(incubate/nn/layer/fused_transformer.py:1022) — generalized to
iteration-level scheduling (cf. Orca's continuous batching, OSDI '22,
and vLLM's paged KV cache, SOSP '23): requests join and leave the
running batch between decode steps instead of start-and-finish
together.

TPU-native design (everything jit-shaped, nothing dynamic on device):

- **Slot pool.** A fixed pool of N decode slots backed by one donated
  stacked KV cache ({"k","v"} buffers of [L, N, max_len, KV, hd] — the
  k/v pair realizes the single [L, 2, N, ...] buffer of the design
  with per-leaf donation, so XLA aliases both across ticks and the
  cache never leaves the device). All writes are in-place
  `dynamic_update_slice`es (kernels/decode_attention.write_kv).
- **One jitted mixed decode step.** Every tick advances ALL slots one
  token under per-slot position/active masks: the per-row-position
  `forward_cached` (models/gpt.py, models/llama.py) runs the N tokens
  as one batch, and greedy + temperature/top-k sampling happens inside
  the jit (per-request PRNG streams derived by folding the request id
  and token index into the engine key, so sampled streams are
  reproducible regardless of slot placement or batch composition).
  The tick's signature is shape-stable -> one trace per sampling mode
  (greedy-only ticks skip the sampling machinery via a static flag)
  for the engine's lifetime.
- **Bucketed prefill.** Prompts pad to the power-of-two bucket
  (models/decode.prompt_bucket — the same policy as the bucketed
  greedy driver, which is what makes engine token streams
  bit-identical to per-request `greedy_generate`); the true length and
  target slot ride through the trace as scalars, so any prompt length
  hits one of ~log(max_len) compiled executables.
- **Python-side scheduler.** Admission queue, slot allocation,
  EOS/max-token/cache-full eviction, and mid-decode join of new
  requests into freed slots all happen on the host between ticks; the
  device only ever sees the fixed-shape tick.

Stale cache contents (a freed slot's previous request, bucket-pad
garbage) are never attended: the decode-attention mask admits cache
slots <= the query's own position only, and decode writes overwrite
the pad region in order (kernels/decode_attention.py).

SLO guardrails (the robustness layer around the scheduler — the
serving analog of parallel/resilience.py's skip/rollback/watchdog, cf.
the reference's predictor error handling and the per-request isolation
requirement of Orca/vLLM-class serving stacks):

- **Admission control.** `max_queue` bounds the queue; an over-full
  submit raises a typed `BackpressureError` (policy "reject") or sheds
  the oldest queued request (policy "shed_oldest"); `queue_ttl_s`
  expires requests that wait too long. `Request.cancel()` frees the
  slot mid-decode.
- **Deadlines.** Per-request wall (`deadline_s`) and engine-tick
  (`deadline_ticks`) deadlines are enforced by the scheduler; every
  submitted request resolves EXACTLY ONCE with a terminal
  `finish_reason` from {eos, length, timeout, cancelled, poisoned,
  evicted} (`TERMINAL_REASONS`) — `_finish` is the one place the
  transition happens.
- **Poisoned-slot quarantine.** With `guardrails=True` (default) the
  decode tick checks `isfinite` over each slot's logit row IN-JIT and
  folds the verdict into the sampled token (`-1` sentinel — real ids
  are never negative), so the one-host-pull-per-tick invariant and the
  trace-count ceilings are untouched. The host evicts only the
  poisoned slot (`finish_reason="poisoned"`); co-batched streams are
  bit-identical because per-slot attention and per-request PRNG
  streams never mix rows. Prefill guards its first-token logits the
  same way.
- **Self-healing tick.** The two device calls (+ the one host pull)
  run under bounded retry/backoff; a failed tick resyncs `_dstate`
  from the host mirrors (`_dirty=True`) — the mirrors only advance
  AFTER a successful pull, so a re-run of the tick is idempotent
  (same state -> same KV writes) and engine state can never desync. A
  hung pull (watchdog — parallel/resilience.WatchdogPuller, the
  persistent-thread variant of the trainer's pull guard) or an
  exhausted retry budget triggers `_hard_reset`: every in-flight
  request terminates as "evicted" and the cache is reallocated.
  Every serving fault dumps a flight-recorder black box
  (profiler/flight_recorder.py).

Paged KV cache (kv_layout="paged", selectable via
PADDLE_TPU_DECODE_ATTN_IMPL=paged / the kernel registry — the
capacity layer, cf. vLLM's PagedAttention SOSP '23 and SGLang's
RadixAttention):

- **Block pool.** K/V live in fixed-size pages ({"k","v"} buffers of
  [L, num_pages, page_size, KV, hd]) instead of one dense
  [L, N, max_len, ...] block; a device-resident per-slot page table
  ("pt" [N, max_pages] int32, riding the donated cache dict) maps
  logical positions to physical pages. HBM scales with TOKENS HELD,
  not num_slots * max_len — the concurrent-stream capacity lever.
  Page 0 is reserved scratch: freed slots and out-of-range positions
  write there, and the position mask keeps its garbage at an exact
  softmax 0. All allocation/refcount/free runs on the host scheduler
  (`_PagePool`) between ticks; the jitted tick only ever sees
  gather/scatter indexing (kernels/decode_attention.gather_pages /
  write_kv_paged) — bit-identical streams vs the dense layout.
- **Prefix sharing + copy-on-write.** Admission hashes the prompt per
  page (a rolled prefix hash: page j's key covers tokens
  [0, (j+1)*page_size)) and maps already-materialized pages instead
  of recomputing them, bumping refcounts; the suffix (always >= 1
  token, so the first-token logits are always computed) prefills
  normally. A slot that must WRITE into a shared/registered page
  first materializes a private copy (`_ensure_private` — the COW
  seam, one jitted in-pool page copy). Finished requests' registered
  pages linger in an LRU "cached" state (refcount 0, evictable on
  demand), so a system prompt's pages survive across request
  lifetimes — the RadixAttention-style cross-request reuse.
- **Chunked prefill.** Prompts whose un-shared suffix exceeds
  `prefill_chunk` split into chunks run ONE PER TICK, interleaved
  with the decode tick, so a max-length prompt can never stall
  co-batched streams past their inter-token deadline. Chunks reuse
  the bucketed-prefill trace policy (power-of-two chunk buckets,
  traced true_len/start/slot), so the prefill executable ceiling is
  unchanged.
- **Pool-exhaustion admission.** Every admission RESERVES its
  worst-case page need (minus shared credit) up front; a request
  that cannot reserve stays queued (never a wedged slot), and one
  that could never fit the configured pool raises the typed
  `PoolExhaustedError` at submit.

Speculative decoding (spec_decode="spec" / env PADDLE_TPU_SPEC_DECODE
/ the "spec_decode" registry kernel — the single-stream latency layer,
cf. Leviathan et al. 2023; OFF by default):

- **Self-draft propose + one-pass verify, one tick.** Each tick runs
  `gamma` truncated-depth draft steps (the first `draft_layers` layers
  of the stacked scan, sharing the target's params and KV cache/pages
  — inference/spec_decode.py) and ONE full-depth verify pass over all
  gamma+1 positions, accepting drafts by the greedy rule
  (models/decode.greedy_accept). A tick emits 1..gamma+1 tokens, every
  one of them the TARGET model's own argmax — greedy streams are
  bit-identical to the non-spec engine on both cache layouts.
- **Invariants preserved.** Still ONE host pull per tick (the
  [N, gamma+1] emission matrix: col 0 = token or -1 quarantine
  sentinel, accepted tokens, then the SPEC_PAD fill); still <= 2
  decode traces (gamma/draft_layers baked per engine, `sampling` the
  only static flag); exactly-once unchanged (mid-block EOS/length
  finishes drop the unconsumed tail, exactly what non-spec would
  never have generated). Sampled slots ride the same tick, emitting
  one reproducible token from verify row 0 (mixed spec/non-spec
  batches) — multi-token rejection sampling is deliberately not
  implemented (it would change sampled streams vs non-spec).
- **Paged interplay.** The tick's write span (gamma+1 positions)
  prepares pages up front, clamped to the request's envelope;
  rejected drafts' pages roll back to the pool after acceptance
  (`_rollback_spec_pages`), so speculation never inflates a slot's
  page footprint between ticks. Draft positions past the envelope
  scatter to the scratch page through the unmapped table.
- **Degradation, not quarantine, on draft failure.** Non-finite DRAFT
  logits force acceptance 0 for that slot (verify row 0 — the
  target's own logits — still emits); only target-model non-finite
  logits quarantine, and only over emitted rows. testing/faults.py
  `draft_nan` + tools/chaos_serving.py drill this.

Tensor-parallel serving (mesh= — the scale-UP layer, cf. the
reference's hybrid-parallel fleet topology
fleet/base/topology.py:54,140 applied to the decode path, and
SNIPPETS.md [3]'s PartitionSpec layout):

- **One engine, one mesh.** `mesh=build_mesh({'tp': N})` shards THIS
  engine's jitted bodies (decode tick, bucketed/chunked prefill, COW
  copy, spec tick) over the mesh's `tp` axis: params per the family's
  module-level SERVING_PARAM_SPECS (the training PARAM_SPECS TP split
  remapped by parallel.mesh.tp_specs — column-parallel qkv/up,
  row-parallel out/down, vocab-parallel embedding), the KV cache/page
  pool head-sharded per kernels/decode_attention.cache_pspecs (the
  page table replicated; a KV-head count the tp degree doesn't divide
  degrades that leaf to replicated — deep GQA), the per-slot decode
  state replicated. GSPMD inserts the two activation all-reduces per
  layer the reference's mp_ops issues by hand.
- **Invariants per mesh.** Still ONE host pull per tick (the pulled
  token array is replicated — one small fetch); zero recompiles after
  warmup (`_pin_cache` pins every jitted body's returned cache leaves
  to their input NamedShardings, so donation aliases exactly and
  propagation heuristics can't shift layouts between calls); every
  host->device upload routes through `_rep` (replicated device_put)
  so placements are mesh-consistent by construction. Token streams
  are BIT-IDENTICAL to the unsharded engine (greedy argmax and the
  partitionable-threefry sampled path both survive sharding — the
  8-virtual-device CPU-mesh suite tests/test_tp_serving.py pins it).
- **Composition.** tp composes with everything above: paged pool,
  chunked prefill, speculative decode (the draft's first-K-layers
  cache view inherits the head sharding). Horizontal scaling stacks
  on top via the replicated-engine router (inference/router.py):
  data-parallel engine replicas behind least-loaded admission —
  dp(router) x tp(engine). parallel.planner.plan_serving_tp prices
  when tp pays (the decode tick is weight-bandwidth bound; a model
  bigger than one chip forces tp > 1).

Quantized serving (quant="int8" / env PADDLE_TPU_QUANT / the
"quant_matmul" registry kernel — the weight-HBM layer, cf. the
reference PTQ driver's channel_wise_abs_max weight path; OFF by
default):

- **Weight-only int8, quantize-at-build.** The engine rewrites its
  params tree once at construction (quantization/serving.py): every
  stacked matmul weight in the family's QUANT_LEAVES (attention
  qkv/proj, MLP in/out) becomes an int8 `<name>_q` plus per-output-
  channel fp32 `<name>_scale` (abs-max/127, the ready dequant
  multiplier), the tied LM head gets a transposed int8 copy
  (`head_q`/`head_scale`) while `wte` stays fp for the embedding
  gather, and the fp leaves are DROPPED — weight HBM falls to ~0.26x
  (f32) / ~0.52x (bf16) for the block weights, which compounds with
  the paged pool (more KV pages at fixed HBM) and tp (bigger models
  per chip).
- **Dequant inside the matmul.** The cached forwards route every
  block matmul through kernels/quant_matmul.leaf_matmul, which picks
  the int8 pair up FROM THE TREE — no flag reaches the jitted bodies,
  so the tick invariants (one host pull, trace ceilings, donation)
  are untouched and dense/paged/spec-draft/tp compose for free. The
  fused dequant-matmul runs as 'xla' (portable, the CPU-tested real
  path) or 'pallas' (hand-tiled, int8->f32 in registers), selected
  via env > registry > 'xla'.
- **Determinism tiers.** Weight-only dequant is deterministic: a
  quantized engine's streams are BIT-IDENTICAL across layouts and
  meshes (dense/paged, spec on/off, tp degrees), and the Pallas and
  XLA impls are bitwise-identical to each other. Versus the fp
  engine, streams carry a measured logit-error budget instead
  (BASELINE.md "Quantized serving"); greedy streams may diverge —
  that is the accuracy/HBM trade, recorded, not hidden.
- **Kill switch + evidence gate.** PADDLE_TPU_QUANT off-values
  disable quantization for new engines even when quant="int8"
  (unrecognized values fail SAFE to off); adoption into the registry
  goes through tools/bench_serving.py --quant --adopt, which refuses
  unless weight bytes <= 0.55x fp AND tokens/s >= 0.95x fp.

Observability: serving.* monitor counters/gauges (slot occupancy,
queue depth, tokens emitted, prefills, decode ticks, plus
rejected/timeout/cancelled/poisoned/evicted/retries/faults, the
queue_wait_ms HISTOGRAM (bounded reservoir, p50/p95/p99 in
snapshots), the kv-pool surface: pages_in_use /
pages_shared gauges, cow_copies / prefill_chunks counters, and the
speculative surface: spec_proposed / spec_accepted counters + the
per-engine spec_accept_rate gauge), in-tick DEVICE telemetry
(telemetry= — the TICK_FIELDS int32 row computed in-jit and riding
the tick's one host pull; profiler/serving_telemetry, records via
tick_records() / telemetry_jsonl=), request-scoped tracing
(tracing= — parented spans submit -> prefill chunks -> decode ->
the exactly-once terminal _finish; profiler/tracing) and
RecordEvent spans around every prefill/decode tick —
tools/telemetry_report.py summarizes them (including TTFT /
inter-token-latency percentiles from `export_slo_jsonl` and a
"kv pool" block), tools/bench_serving.py measures the engine against
sequential per-request decode (--capacity races paged vs dense at
equal HBM), and tools/chaos_serving.py is the executable acceptance
test for the guardrails.
"""
from __future__ import annotations

import collections
import dataclasses
import functools
import hashlib
import json
import sys
import time
from typing import Callable, List, Optional, Sequence

import numpy as np
import jax
import jax.numpy as jnp

from ..models.decode import prompt_bucket
from ..profiler import RecordEvent, monitor

__all__ = ["ServingEngine", "Request", "ModelFamily", "family_for",
           "create_serving_engine", "BackpressureError",
           "PoolExhaustedError", "ServingFaultError", "TERMINAL_REASONS"]

# every submitted request ends in exactly one of these (the
# finish-reason state machine — docs/serving.md "Robustness")
TERMINAL_REASONS = frozenset(
    {"eos", "length", "timeout", "cancelled", "poisoned", "evicted"})

# fault-injection seam (paddle_tpu.testing.faults.install wires it):
# called with the tick index about to run, returns an action dict
# ({"poison_slot": i} | {"draft_poison_slot": i} | {"stall_s": s} |
# {"raise_prefill": True} | {"raise_decode": True} |
# {"raise_cow": True} | {"raise_migrate": True}). Production code
# never sets it.
_FAULT_HOOK: Optional[Callable[[int], dict]] = None


class BackpressureError(RuntimeError):
    """submit() refused: the admission queue is at max_queue (policy
    "reject"). Carries .queue_depth so callers can report/shed."""

    def __init__(self, msg: str, queue_depth: int = 0):
        super().__init__(msg)
        self.queue_depth = queue_depth


class PoolExhaustedError(RuntimeError):
    """submit() refused: the request's worst-case page need exceeds
    the ENTIRE configured pool — it could never be admitted. Requests
    that merely have to wait for pages are queued, not refused."""

    def __init__(self, msg: str, pages_needed: int = 0,
                 pages_total: int = 0):
        super().__init__(msg)
        self.pages_needed = pages_needed
        self.pages_total = pages_total


class ServingFaultError(RuntimeError):
    """An injected serving fault (testing.faults prefill_raise /
    decode_raise / cow_raise) — raised at the device-call seam so the
    retry path exercises exactly what an organic dispatch failure
    would."""


# --------------------------------------------------------------- families
@dataclasses.dataclass(frozen=True)
class ModelFamily:
    """The seam a model family exposes to the engine: a cached forward
    that accepts per-row positions (slot-indexed writes) and a cache
    factory. Both flagship decoders qualify; any future family that
    implements the same contract plugs in here. `serving_specs` is the
    family's module-level tensor-parallel spec table (leaf name ->
    PartitionSpec over the serving mesh's 'tp' axis — models/gpt.py /
    models/llama.py SERVING_PARAM_SPECS); None means the family cannot
    shard (mesh= is then refused)."""
    name: str
    forward_cached: Callable    # (params, tokens[B,T], cache, pos, cfg)
    init_cache: Callable        # (cfg, batch, max_len) -> {"k","v"}
    serving_specs: Optional[dict] = None


def family_for(name: str) -> ModelFamily:
    if name == "gpt":
        from ..models import gpt
        return ModelFamily("gpt", gpt.gpt_forward_cached,
                           gpt.init_kv_cache, gpt.SERVING_PARAM_SPECS)
    if name == "llama":
        from ..models import llama
        return ModelFamily("llama", llama.llama_forward_cached,
                           llama.init_kv_cache,
                           llama.SERVING_PARAM_SPECS)
    raise ValueError(f"unknown model family {name!r} (gpt|llama)")


# -------------------------------------------------------------- page pool
class _PagePool:
    """Host-side allocator for the paged KV pool (the scheduler half of
    the vLLM block manager). Every page is in exactly one state:

    - free      never registered; on the free list;
    - live      refcount > 0 (mapped by >= 1 slot page tables);
    - cached    refcount == 0 but registered under a prompt-prefix key
                (LRU; evictable on demand — cross-request prefix reuse).

    Page 0 is the reserved scratch page (permanently live, never
    handed out): freed slots' table rows and out-of-range positions
    point at it, so stray scatter writes land in garbage the position
    mask never admits.

    `reserved` tracks admission-time worst-case reservations not yet
    turned into allocations — `available()` is what a NEW admission
    may claim without starving an already-admitted slot."""

    def __init__(self, num_pages: int, page_size: int):
        if num_pages < 2:
            raise ValueError(f"num_pages must be >= 2 (page 0 is "
                             f"reserved scratch); got {num_pages}")
        self.num_pages = int(num_pages)
        self.page_size = int(page_size)
        self.ref = np.zeros(num_pages, np.int64)
        self.ref[0] = 1                      # scratch: pinned forever
        # pop() takes from the end -> low page ids hand out first
        self.free: List[int] = list(range(num_pages - 1, 0, -1))
        self.cached: "collections.OrderedDict[int, tuple]" = \
            collections.OrderedDict()        # page_id -> key, LRU order
        self.by_key: dict = {}               # prefix key -> page_id
        self.key_of: dict = {}               # page_id -> prefix key
        self.reserved = 0                    # admission reservations
        # eviction tap (host-tier KV offload): called as on_evict(pid,
        # key) just before a registered page's LRU eviction drops its
        # prefix-map entry — the engine's spill hook copies the page to
        # host there, so "evicted from device" means "demoted to the
        # host tier", not "gone"
        self.on_evict = None

    def available(self) -> int:
        """Pages a new admission may still reserve: free + evictable
        cached, minus what prior admissions already reserved."""
        return len(self.free) + len(self.cached) - self.reserved

    def alloc(self) -> int:
        """One private page (ref=1), evicting the LRU cached page (and
        its prefix-map entry) when the free list is dry. Raises
        PoolExhaustedError when nothing is evictable — unreachable for
        reserved admissions by construction."""
        if self.free:
            pid = self.free.pop()
        elif self.cached:
            pid, key = self.cached.popitem(last=False)     # LRU
            if self.on_evict is not None:
                self.on_evict(pid, key)
            del self.by_key[key]
            del self.key_of[pid]
        else:
            raise PoolExhaustedError(
                "page pool exhausted (no free or evictable page)",
                pages_needed=1, pages_total=self.num_pages)
        self.ref[pid] = 1
        return pid

    def retain(self, pid: int) -> None:
        """One more reference (prefix sharing): a cached page comes
        back live; its prefix-map registration survives."""
        if self.ref[pid] == 0:
            self.cached.pop(pid, None)
        self.ref[pid] += 1

    def release(self, pid: int) -> None:
        """Drop one reference. At zero a registered page parks in the
        LRU cache (prefix reuse across request lifetimes); an
        unregistered one returns to the free list."""
        if pid == 0:
            return                           # scratch never releases
        self.ref[pid] -= 1
        assert self.ref[pid] >= 0, f"refcount underflow on page {pid}"
        if self.ref[pid] == 0:
            key = self.key_of.get(pid)
            if key is not None:
                self.cached[pid] = key
                self.cached.move_to_end(pid)
            else:
                self.free.append(pid)

    def register(self, pid: int, key) -> None:
        """Publish `pid` under the prompt-prefix `key` (first writer
        wins — a racing identical prefix keeps its private copy)."""
        if key not in self.by_key and pid not in self.key_of:
            self.by_key[key] = pid
            self.key_of[pid] = key

    def lookup(self, key) -> Optional[int]:
        return self.by_key.get(key)

    def is_frozen(self, pid: int) -> bool:
        """True when writing `pid` requires a private copy first:
        shared (ref > 1) or published in the prefix map (another slot
        may map it at any moment)."""
        return self.ref[pid] > 1 or pid in self.key_of

    def stats(self) -> dict:
        live = int((self.ref[1:] > 0).sum())
        return {"num_pages": self.num_pages,
                "page_size": self.page_size,
                "pages_in_use": live,
                "pages_free": len(self.free),
                "pages_cached": len(self.cached),
                "pages_shared": int((self.ref[1:] > 1).sum()),
                "pages_reserved": int(self.reserved)}


def _prefix_key(prompt: np.ndarray, n: int) -> tuple:
    """The rolled prompt-prefix hash for the page ending at token `n`:
    identical token prefixes -> identical K/V bits (causality), so the
    digest of tokens [0, n) keys a reusable page. Length rides in the
    key so a digest collision across lengths cannot alias."""
    return (n, hashlib.blake2b(prompt[:n].tobytes(),
                               digest_size=16).digest())


# --------------------------------------------------------------- requests
class Request:
    """One generation request riding through the engine."""

    __slots__ = ("id", "prompt", "max_new_tokens", "temperature",
                 "top_k", "eos_id", "tokens", "done", "finish_reason",
                 "slot", "deadline_s", "deadline_ticks", "t_submit",
                 "_tick_submit", "_t_last", "_engine", "_pf_next",
                 "shared_tokens", "_pfx_keys", "trace", "_sp_queue",
                 "_sp_decode", "tenant", "priority")

    def __init__(self, req_id, prompt, max_new_tokens, temperature,
                 top_k, eos_id, deadline_s=None, deadline_ticks=None,
                 tenant="default", priority=0):
        self.id = req_id
        self.prompt = prompt
        self.max_new_tokens = max_new_tokens
        self.temperature = temperature
        self.top_k = top_k
        self.eos_id = eos_id
        # multi-tenant admission labels (inference/admission.py): the
        # ENGINE carries them untouched — quotas/fairness/preemption
        # are router policy; they ride snapshots so a suspended or
        # migrated stream keeps its class
        self.tenant = tenant
        self.priority = int(priority)
        self.deadline_s = deadline_s       # wall seconds from submit
        self.deadline_ticks = deadline_ticks  # engine ticks from submit
        self.tokens: List[int] = []     # generated ids, in order
        self.done = False
        self.finish_reason: Optional[str] = None
        self.slot: Optional[int] = None
        self.t_submit = 0.0
        self._tick_submit = 0
        self._t_last = 0.0              # last emission (SLO samples)
        self._engine = None
        self._pf_next = None            # next chunked-prefill position
        self._pfx_keys = None           # memoized per-page prefix hashes
        self.shared_tokens = 0          # prompt tokens served from
        #                                 shared pages (prefix reuse)
        self.trace = None               # RequestTrace (tracing=True /
        #                                 router-passed; profiler/tracing)
        self._sp_queue = None           # open queue-span id
        self._sp_decode = None          # open decode-span id

    def cancel(self) -> bool:
        """Terminate this request NOW (finish_reason "cancelled"):
        dequeues it if still waiting, frees its slot if mid-decode.
        Returns False when the request already resolved."""
        eng = self._engine
        return False if eng is None else eng.cancel(self)

    def __repr__(self):
        return (f"Request(id={self.id}, len={len(self.prompt)}, "
                f"gen={len(self.tokens)}/{self.max_new_tokens}, "
                f"done={self.done})")


# ------------------------------------------------------- in-jit sampling
def _slot_keys(base_key, req_ids, gen_idx):
    """Per-slot PRNG keys: fold (request id, token index) into the
    engine key — streams depend on the request, never on slot placement
    or batch composition."""
    def one(rid, gi):
        return jax.random.fold_in(jax.random.fold_in(base_key, rid), gi)
    return jax.vmap(one)(req_ids, gen_idx)


def _sample(lg, temps, top_ks, keys, max_top_k: int):
    """lg [N,V] f32 -> next token [N] int32. Greedy where temp <= 0
    (bit-identical to the greedy driver's argmax); otherwise
    temperature softmax sampling, truncated to the request's top_k
    (<= the engine's static max_top_k) when top_k > 0."""
    greedy = jnp.argmax(lg, axis=-1)
    safe_t = jnp.maximum(temps, 1e-6)[:, None]
    full = jax.vmap(jax.random.categorical)(keys, lg / safe_t)
    sampled = full
    if max_top_k > 0:
        vals, idx = jax.lax.top_k(lg, max_top_k)           # [N,K]
        k_eff = jnp.minimum(jnp.where(top_ks <= 0, max_top_k, top_ks),
                            max_top_k)
        masked = jnp.where(jnp.arange(max_top_k)[None, :] < k_eff[:, None],
                           vals, -jnp.inf)
        choice = jax.vmap(jax.random.categorical)(keys, masked / safe_t)
        trunc = jnp.take_along_axis(idx, choice[:, None], axis=1)[:, 0]
        sampled = jnp.where(top_ks > 0, trunc, full)
    return jnp.where(temps <= 0.0, greedy, sampled).astype(jnp.int32)


def _pin_cache(cache, pin):
    """Pin the returned cache leaves to their input NamedShardings
    (tensor-parallel serving, `mesh=`): GSPMD would usually propagate
    the same layout, but pinning makes it a contract — the donated
    buffers alias exactly (out sharding == in sharding) and the tick's
    executable count cannot drift with propagation heuristics. `pin`
    is a {leaf: NamedSharding} dict closed over the jit (hashable,
    non-traced); None/missing leaves pass through untouched."""
    if not pin:
        return cache
    return {k: (jax.lax.with_sharding_constraint(v, pin[k])
                if pin.get(k) is not None else v)
            for k, v in cache.items()}


# --------------------------------------------------------- jitted bodies
# slot-state tuple riding through the decode tick (all [N], device-
# resident and DONATED alongside the cache — the host only downloads
# the sampled tokens, one small pull per tick)
#   (cur_tok, positions, active, temps, top_ks, req_ids, gen_idx)
def _decode_tick(params, cache, state, base_key, poison, *, fwd, cfg,
                 max_top_k, sampling, guard, oor_pos=None,
                 cache_pin=None, tele=False):
    """THE mixed step: all N slots advance one token. Each slot's
    current token is written at its own position; sampling runs in-jit;
    inactive slots compute too (fixed shape) but their output is masked
    and their slot region is overwritten at the next prefill.
    `sampling` is STATIC: greedy-only ticks skip the key-fold +
    categorical machinery entirely (~0.4 ms/tick on the CPU rung), so
    the tick has at most two traces for the engine's lifetime.
    `guard` is baked per engine (guardrails=): the per-row isfinite
    quarantine verdict folds into the token as a -1 sentinel (real ids
    are never negative), so flagging costs no extra host pull and no
    extra trace. `poison` [N] is the fault-injection multiplier
    (all-ones in production; testing.faults nan_logits sets one lane to
    nan INSIDE the jit so injected and organic non-finite logits
    exercise the exact same guard); multiplying by 1.0 is exact in
    IEEE fp, so guarded greedy/sampled streams stay bit-identical.
    `tele` (static, baked per engine) additionally returns the
    TICK_FIELDS int32 row (profiler/serving_telemetry) computed from
    values the tick already holds — it rides the same host pull as
    the token array and never touches the stream math."""
    toks, positions, active, temps, top_ks, req_ids, gen_idx = state
    # under the paged layout the pool is SHARED across rows, so an
    # inactive row (mid-chunked-prefill, its table already mapping
    # real — possibly shared — pages) must not scatter its garbage
    # K/V through the table: route its write past the table, onto the
    # scratch page (oor_pos = max_pages * page_size; dense rows own
    # their cache row outright, so oor_pos stays None there)
    fpos = (positions if oor_pos is None
            else jnp.where(active, positions, oor_pos))
    logits, cache = fwd(params, toks[:, None], cache, fpos, cfg)
    lg = logits[:, 0].astype(jnp.float32)
    if guard:
        lg = lg * poison[:, None]
    if sampling:
        keys = _slot_keys(base_key, req_ids, gen_idx)
        nxt = _sample(lg, temps, top_ks, keys, max_top_k)
    else:
        nxt = jnp.argmax(lg, axis=-1).astype(jnp.int32)
    nxt = jnp.where(active, nxt, 0).astype(jnp.int32)
    bad = jnp.zeros_like(active)
    if guard:
        row_ok = jnp.all(jnp.isfinite(lg), axis=-1)
        bad = active & ~row_ok
        nxt = jnp.where(bad, -1, nxt)
    inc = active.astype(jnp.int32)
    state = (nxt, positions + inc, active, temps, top_ks, req_ids,
             gen_idx + inc)
    if not tele:
        return nxt, _pin_cache(cache, cache_pin), state
    # in-tick telemetry row riding the SAME host pull as `nxt` (zero
    # extra transfers — profiler/serving_telemetry): what the tick
    # emitted/advanced/flagged, plus the attention tap
    from ..kernels.decode_attention import attended_tokens
    from ..profiler.serving_telemetry import pack_tick_fields
    trow = pack_tick_fields(
        tokens=jnp.sum(active & ~bad), active=jnp.sum(active),
        poisoned=jnp.sum(bad),
        attended=attended_tokens(positions, active))
    return nxt, trow, _pin_cache(cache, cache_pin), state


def _prefill_slot(params, cache, padded, true_len, slot, temps, top_ks,
                  req_ids, base_key, *, fwd, init_cache, cfg, max_top_k,
                  sampling, guard, cache_pin=None):
    """Bucketed prefill of ONE request into slot `slot`: run the padded
    prompt through a fresh single-row BUCKET-length cache (bit-identical
    K/V and logits to the greedy driver's full-length prefill — the
    masked softmax gives padded/absent positions an exact 0), sample the
    first token from the last REAL position's logits, and write the row
    into the pool, wiping the slot's previous occupant up to the bucket
    (anything staler is masked until decode overwrites it). Trace key:
    the bucket length only (true_len/slot are traced scalars). With
    `guard` (static, baked per engine) a non-finite first-token logit
    row folds into a -1 sentinel token — the quarantine verdict rides
    the pull the admission already makes."""
    mini = init_cache(cfg, 1, padded.shape[1])
    logits, mini = fwd(params, padded, mini, 0, cfg)
    last = jax.lax.dynamic_slice_in_dim(
        logits, true_len - 1, 1, axis=1)[:, 0].astype(jnp.float32)
    if sampling:
        keys = _slot_keys(base_key, req_ids, jnp.zeros((1,), jnp.int32))
        first = _sample(last, temps, top_ks, keys, max_top_k)[0]
    else:
        first = jnp.argmax(last, axis=-1).astype(jnp.int32)[0]
    if guard:
        first = jnp.where(jnp.all(jnp.isfinite(last)), first, -1)
    cache = {
        "k": jax.lax.dynamic_update_slice(
            cache["k"], mini["k"], (0, slot, 0, 0, 0)),
        "v": jax.lax.dynamic_update_slice(
            cache["v"], mini["v"], (0, slot, 0, 0, 0)),
    }
    return first, _pin_cache(cache, cache_pin)


def _prefill_chunk(params, cache, padded, true_len, start, slot, temps,
                   top_ks, req_ids, base_key, *, fwd, cfg, max_top_k,
                   sampling, guard, cache_pin=None):
    """Paged/chunked prefill of ONE chunk into slot `slot`: run the
    padded chunk [1, cb] at absolute positions start.. against the
    slot's single-row paged view (its page-table row sliced out of the
    pool's "pt"), scattering the chunk's K/V into the pool pages, and
    sample a token from the chunk's LAST REAL position — meaningful
    only for the prompt's final chunk (logits at t0-1); the host
    ignores it (and skips the pull entirely) for earlier chunks.
    Trace key: the chunk bucket length only (true_len/start/slot are
    traced scalars), so chunking reuses the bucketed-prefill
    executable ceiling. Bit-parity: per-position K/V and the masked
    softmax are bit-identical whether the prompt runs as one pass or
    as chunks (pad/absent positions contribute an exact 0)."""
    row = jax.lax.dynamic_slice_in_dim(cache["pt"], slot, 1, axis=0)
    sub = {"k": cache["k"], "v": cache["v"], "pt": row}
    posv = jnp.reshape(start, (1,)).astype(jnp.int32)
    logits, sub = fwd(params, padded, sub, posv, cfg)
    last = jax.lax.dynamic_slice_in_dim(
        logits, true_len - 1, 1, axis=1)[:, 0].astype(jnp.float32)
    if sampling:
        keys = _slot_keys(base_key, req_ids, jnp.zeros((1,), jnp.int32))
        first = _sample(last, temps, top_ks, keys, max_top_k)[0]
    else:
        first = jnp.argmax(last, axis=-1).astype(jnp.int32)[0]
    if guard:
        first = jnp.where(jnp.all(jnp.isfinite(last)), first, -1)
    out = {"k": sub["k"], "v": sub["v"], "pt": cache["pt"]}
    return first, _pin_cache(out, cache_pin)


def _cow_copy(cache, src, dst, *, cache_pin=None):
    """Copy page `src` onto page `dst` across every layer of the pool
    (both k and v) — THE copy-on-write materialization, one jitted
    in-pool dynamic slice/update on the donated buffers; src/dst are
    traced scalars so the engine holds exactly one trace of this."""
    out = dict(cache)
    for key in ("k", "v"):
        pg = jax.lax.dynamic_slice_in_dim(cache[key], src, 1, axis=1)
        out[key] = jax.lax.dynamic_update_slice(
            cache[key], pg, (0, dst, 0, 0, 0))
    return _pin_cache(out, cache_pin)


# ----------------------------------------------------------- the engine
class ServingEngine:
    """Iteration-level scheduler over a fixed slot pool.

    >>> eng = ServingEngine(params, cfg, family="gpt", num_slots=8)
    >>> req = eng.submit(prompt_ids, max_new_tokens=32)
    >>> while eng.has_work():
    ...     for r, tok in eng.step():   # (request, token) emissions
    ...         ...
    >>> req.tokens

    `generate(prompts, ...)` wraps submit+drain for batch use.
    """

    def __init__(self, params, cfg, family="gpt", num_slots: int = 8,
                 max_len: Optional[int] = None, max_top_k: int = 0,
                 seed: int = 0, bucket_lo: int = 8,
                 decode_unroll: int = 0, max_queue: int = 0,
                 queue_policy: str = "reject", queue_ttl_s: float = 0.0,
                 watchdog_timeout: float = 0.0, retries: int = 2,
                 backoff_base: float = 0.05, backoff_max: float = 2.0,
                 guardrails: bool = True, kv_layout: str = "auto",
                 page_size: int = 16, num_pages: int = 0,
                 prefill_chunk: int = 0, prefix_sharing: bool = True,
                 spec_decode: str = "auto", gamma: int = 4,
                 draft_layers: int = 0, mesh=None, tp_axis: str = "tp",
                 quant: str = "auto", telemetry: str = "auto",
                 telemetry_jsonl: Optional[str] = None,
                 telemetry_every: int = 32, tracing: bool = False,
                 multi_tick: int = 0, host_kv_bytes: int = 0):
        self.family = (family_for(family) if isinstance(family, str)
                       else family)
        self.cfg = cfg
        self.num_slots = int(num_slots)
        # --------------------------------------- tensor-parallel serving
        # mesh= shards THIS engine's decode tick over `tp_axis`: params
        # per the family's module-level SERVING_PARAM_SPECS (the
        # training TP split remapped — parallel.mesh.tp_specs), the KV
        # cache/page pool per kernels/decode_attention.cache_pspecs
        # (head-sharded, shape-aware degrade to replicated), page
        # tables and the per-slot decode state replicated. Every
        # host->device upload goes through _rep so the jitted bodies
        # only ever see mesh-consistent placements; the host pull stays
        # ONE small (replicated) array per tick per mesh.
        self.mesh = mesh
        self.tp_axis = str(tp_axis)
        if mesh is not None:
            if self.tp_axis not in mesh.axis_names:
                raise ValueError(
                    f"mesh {dict(mesh.shape)} has no {self.tp_axis!r} "
                    "axis (build it via parallel.mesh.build_mesh("
                    "{'tp': N}) or pass tp_axis=)")
            if self.family.serving_specs is None:
                raise ValueError(
                    f"family {self.family.name!r} has no "
                    "SERVING_PARAM_SPECS — it cannot run tensor-"
                    "parallel (see models/gpt.py)")
        self.tp = int(mesh.shape[self.tp_axis]) if mesh is not None else 1
        self._rep_sharding = None
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec
            self._rep_sharding = NamedSharding(mesh, PartitionSpec())
        # ------------------------------------------- speculative decode
        # knob 'auto' consults env > registry ('spec_decode') > off;
        # the env's off values kill-switch even an explicit 'spec'
        # (inference/spec_decode.resolve_spec)
        from .spec_decode import resolve_spec
        self.spec = resolve_spec(spec_decode)
        # whether drafts CAN run: set_spec_drafts (brownout) may flip
        # self.spec live, but only back up to this construction-time cap
        self._spec_capable = self.spec
        n_layers = int(getattr(cfg, "num_layers", 0))
        self.spec_gamma = int(gamma)
        self.spec_draft_layers = int(draft_layers) or max(1, n_layers // 2)
        if self.spec:
            if self.spec_gamma < 1:
                raise ValueError(f"gamma must be >= 1; got {gamma}")
            if not 1 <= self.spec_draft_layers <= max(n_layers, 1):
                raise ValueError(
                    f"draft_layers ({self.spec_draft_layers}) must be in "
                    f"1..num_layers ({n_layers})")
            import inspect
            try:
                sig = inspect.signature(self.family.forward_cached)
            except (TypeError, ValueError):
                sig = None
            if sig is not None and "layers" not in sig.parameters:
                raise ValueError(
                    f"family {self.family.name!r}: forward_cached does "
                    "not accept layers= — the truncated-depth self-draft "
                    "needs it (see models/gpt.py gpt_forward_cached)")
        # --------------------------------------------- fused multi-tick
        # knob 0/'auto' consults env > registry ('multi_tick') > off;
        # PADDLE_TPU_MULTI_TICK's off values kill-switch even an
        # explicit K (inference/multi_tick.resolve_multi_tick). K is
        # BAKED into the decode executable (a lax.scan of length K), so
        # the jit cache keys of engines with different K never collide.
        from .multi_tick import resolve_multi_tick
        self.mt_k = resolve_multi_tick(multi_tick)
        # per-dispatch emission width: how many tokens one host pull
        # may carry per slot (spec emits gamma+1 columns per tick)
        self._tick_span = self.mt_k * ((self.spec_gamma + 1)
                                       if self.spec else 1)
        # ------------------------------------------------- cache layout
        if kv_layout == "auto":
            from ..kernels.decode_attention import decode_attn_impl
            kv_layout = ("paged" if decode_attn_impl() == "paged"
                         else "dense")
        if kv_layout not in ("dense", "paged"):
            raise ValueError(f"kv_layout {kv_layout!r} "
                             "(auto|dense|paged)")
        self.paged = kv_layout == "paged"
        self.page_size = int(page_size)
        self.prefill_chunk = int(prefill_chunk)
        self.prefix_sharing = bool(prefix_sharing)
        # ------------------------------------------------ SLO guardrails
        if queue_policy not in ("reject", "shed_oldest"):
            raise ValueError(f"queue_policy {queue_policy!r} "
                             "(reject|shed_oldest)")
        self.max_queue = int(max_queue)       # 0 = unbounded
        self.queue_policy = queue_policy
        self.queue_ttl_s = float(queue_ttl_s)  # 0 = no TTL
        self.watchdog_timeout = float(watchdog_timeout)  # 0 = no watchdog
        self.retries = int(retries)           # device-call retry budget
        self.backoff_base = float(backoff_base)
        self.backoff_max = float(backoff_max)
        self.guardrails = bool(guardrails)    # in-jit isfinite quarantine
        self.max_len = int(max_len or cfg.max_seq_len)
        if self.max_len > getattr(cfg, "max_seq_len", self.max_len):
            # positions past the table (gpt wpe / llama rope cache) would
            # CLAMP, silently corrupting every later token
            raise ValueError(
                f"max_len ({self.max_len}) exceeds the model's "
                f"max_seq_len ({cfg.max_seq_len}): position embeddings "
                "beyond the table would clamp, not error")
        self.max_top_k = int(max_top_k)
        self.bucket_lo = int(bucket_lo)
        # --------------------------------------- weight-only int8 quant
        # knob 'auto' consults env > registry ('quant_matmul') > off;
        # PADDLE_TPU_QUANT's off values kill-switch even an explicit
        # 'int8' (kernels/quant_matmul.resolve_quant). Quantization is
        # a LEAF REWRITE at build: the fp matmul weights become
        # <name>_q/<name>_scale pairs (plus the transposed head copy),
        # the cached forwards pick them up from the tree through
        # kernels/quant_matmul.leaf_matmul, and the jitted bodies /
        # tick invariants are untouched — same state tuple, same one
        # pull per tick, same trace ceilings.
        from ..kernels.quant_matmul import resolve_quant
        self.quant = resolve_quant(quant)
        self._serving_specs = self.family.serving_specs
        self._quant_info = None
        if self.quant:
            from ..quantization.serving import quantize_serving_params
            params, qspecs, self._quant_info = quantize_serving_params(
                params, self.family.name, self._serving_specs)
            if self._serving_specs is not None:
                self._serving_specs = qspecs
        self._params = (self._shard_params(params) if mesh is not None
                        else params)
        self._cache_pin = None        # leaf -> NamedSharding under mesh=
        if self.paged:
            if self.page_size < 1:
                raise ValueError(f"page_size must be >= 1; "
                                 f"got {self.page_size}")
            ps = self.page_size
            self.max_pages = -(-self.max_len // ps)      # ceil
            # dense-equivalent capacity by default (+1 scratch); a
            # smaller num_pages is the capacity lever (bench_serving
            # --capacity races paged vs dense at equal HBM)
            self.num_pages = int(num_pages) or \
                self.num_slots * self.max_pages + 1
            self._pool = _PagePool(self.num_pages, ps)
            self._ptab = np.zeros((self.num_slots, self.max_pages),
                                  np.int32)
            self._pt_dirty = False
        self._cache = self._new_cache()
        self._base_key = self._rep(jax.random.PRNGKey(seed))

        # at T=1 the layer scan's cache slice/restack dominates the
        # matvecs: fully unroll shallow stacks (bit-identical numerics —
        # models/gpt.py decode_scan_unroll). 0 = auto, 1 = keep the scan.
        # Auto only applies when the config still carries the field's
        # default (1): an explicitly tuned cfg.decode_scan_unroll wins.
        cfg_unroll = getattr(cfg, "decode_scan_unroll", None)
        if decode_unroll == 0:
            if cfg_unroll not in (None, 1):
                decode_unroll = cfg_unroll
            else:
                layers = getattr(cfg, "num_layers", 0)
                decode_unroll = layers if 0 < layers <= 8 else 1
        run_cfg = cfg
        if cfg_unroll not in (None, decode_unroll):
            try:
                run_cfg = dataclasses.replace(
                    cfg, decode_scan_unroll=decode_unroll)
            except TypeError:        # non-dataclass custom family config
                run_cfg = cfg

        n = self.num_slots
        # host MIRRORS of the slot state (scheduling reads these); the
        # device copies ride donated through the tick and are rebuilt
        # from the mirrors only when admission/eviction dirties them
        self._positions = np.zeros(n, np.int32)   # tokens in each slot
        self._active = np.zeros(n, bool)
        self._cur_tok = np.zeros(n, np.int32)     # last sampled token
        self._temps = np.zeros(n, np.float32)
        self._top_ks = np.zeros(n, np.int32)
        self._req_ids = np.zeros(n, np.int32)
        self._gen_idx = np.zeros(n, np.int32)     # next sample index
        # multi-tick early-exit inputs (EOS id, -1 = none; token
        # budget): host mirrors here, device copies in _daux, rebuilt
        # alongside the state tuple when _dirty (multi_tick.py scans
        # retire slots ON DEVICE by these rules)
        self._eos_ids = np.full(n, -1, np.int32)
        self._max_new = np.zeros(n, np.int32)
        self._daux = None
        self._dstate = None                       # device state tuple
        self._dirty = True
        self._slot_req: List[Optional[Request]] = [None] * n
        self._queue: collections.deque = collections.deque()
        self._next_id = 0
        self._ticks = 0                  # step() calls (fault/deadline clock)
        self._poison_ones = self._rep(np.ones(n, np.float32))  # reused:
        #                  the steady-state tick uploads NO poison array
        # SLO samples (host wall-clock, ms): TTFT includes queue wait;
        # inter-token latency is per-emission, quantized to tick times
        self._slo_ttft: collections.deque = collections.deque(maxlen=8192)
        self._slo_itl: collections.deque = collections.deque(maxlen=8192)

        # ----------------------------------------- in-tick telemetry
        # the decode tick computes the TICK_FIELDS int32 row in-jit and
        # returns it NEXT TO the token array; both ride the ONE host
        # pull the tick already makes (profiler/serving_telemetry —
        # zero extra pulls, zero extra traces, kill switch
        # PADDLE_TPU_SERVING_TELEMETRY). The host joins scheduler-side
        # fields (queue depth, prefilling, pages in use) + tick wall ms
        # into serving_tick records: a bounded in-memory ring
        # (`tick_records()`) and optionally a JSONL stream
        # (`telemetry_jsonl=`, flushed every `telemetry_every` records
        # on a background writer).
        from ..profiler.serving_telemetry import (ServingTelemetry,
                                                  resolve_serving_telemetry)
        self._tick_tele = resolve_serving_telemetry(telemetry)
        self._tick_log = None
        if self._tick_tele:
            self._tick_log = ServingTelemetry(
                path=telemetry_jsonl, every=telemetry_every,
                meta={"family": self.family.name,
                      "layout": "paged" if self.paged else "dense",
                      "spec": bool(self.spec),
                      "quant": "int8" if self.quant else "off",
                      "multi_tick": self.mt_k,
                      "tp": self.tp, "num_slots": self.num_slots,
                      "max_len": self.max_len},
                on_flush=self._publish_tier_gauges)
        # ---------------------------------------- request-scoped traces
        # opt-in (tracing=True): submit() mints a RequestTrace
        # (profiler/tracing) and the scheduler emits parented spans
        # through queue -> prefill chunks -> decode -> the terminal
        # _finish; a router passes its own trace down via submit(_trace=)
        # so routed requests keep ONE tree across dispatch and replay.
        self._tracer = None
        if tracing:
            from ..profiler import tracing as _tracing
            self._tracer = _tracing.tracer()

        self._run_cfg = run_cfg       # the unroll-resolved config the
        #                               jitted bodies close over — kept
        #                               so rebuild_on_mesh re-jits the
        #                               SAME computation on a new mesh
        if self.paged:
            self._slot_reserve = np.zeros(self.num_slots, np.int64)
            self._prefilling: collections.deque = collections.deque()
            self._raise_cow = False          # injected cow_raise fault
        self._raise_migrate = False          # injected migrate_raise fault
        self._make_executables()

        from ..profiler import flight_recorder
        self._flight = flight_recorder.recorder()
        self._puller = None            # lazy persistent watchdog worker

        self._m_occ = monitor.gauge("serving.slot_occupancy")
        self._m_queue = monitor.gauge("serving.queue_depth")
        # queue wait is a DISTRIBUTION (the admission-latency half of
        # TTFT): a last-write-wins gauge hid the tail, the bounded-
        # reservoir histogram snapshots p50/p95/p99
        self._m_qwait = monitor.histogram("serving.queue_wait_ms")
        self._m_tok = monitor.counter("serving.tokens_emitted")
        self._m_pre = monitor.counter("serving.prefills")
        self._m_tick = monitor.counter("serving.decode_ticks")
        self._m_sub = monitor.counter("serving.requests_submitted")
        self._m_done = monitor.counter("serving.requests_completed")
        self._m_rej = monitor.counter("serving.rejected")
        self._m_retry = monitor.counter("serving.retries")
        self._m_fault = monitor.counter("serving.faults")
        self._reason_ctr = {
            "timeout": monitor.counter("serving.timeout"),
            "cancelled": monitor.counter("serving.cancelled"),
            "poisoned": monitor.counter("serving.poisoned"),
            "evicted": monitor.counter("serving.evicted"),
        }
        # kv-pool surface (stay 0 under the dense layout)
        self._m_pages = monitor.gauge("serving.pages_in_use")
        self._m_shared = monitor.gauge("serving.pages_shared")
        self._m_cow = monitor.counter("serving.cow_copies")
        self._m_chunks = monitor.counter("serving.prefill_chunks")
        # kv-pool HBM in bytes, next to pages_in_use: dense = the full
        # preallocated cache (constant, set once); paged = pages_in_use
        # x per-page bytes, republished with the page gauges
        self._m_kv_bytes = monitor.gauge("serving.kv_pool_bytes")
        self._m_oom = monitor.counter("serving.oom_forensics")
        _kb = self._cache["k"]
        if self.paged:
            self._page_bytes = 2 * _kb.nbytes // self.num_pages
            self._publish_pool_gauges()
        else:
            self._m_kv_bytes.set(2 * _kb.nbytes)
        # ------------------------------------------ host-tier KV offload
        # paged + prefix_sharing only: the pool's LRU eviction demotes
        # registered pages to host ndarrays instead of dropping them,
        # and admission swaps them back (inference/host_kv.py). 0 = off;
        # PADDLE_TPU_HOST_KV kill-switches an explicit cap.
        from .host_kv import resolve_host_kv
        self.host_kv_bytes = resolve_host_kv(host_kv_bytes)
        self._host_tier = None
        self._host_stage: dict = {}    # prefix key -> (dk, dv) prefetch
        if self.paged and self.prefix_sharing and self.host_kv_bytes > 0:
            from .host_kv import HostKVTier
            self._host_tier = HostKVTier(self.host_kv_bytes)
            self._pool.on_evict = self._spill_page
        # disaggregation surface: gauges ride the telemetry flush
        # cadence via on_flush (zero extra device pulls)
        self._m_kv_host = monitor.gauge("serving.kv_host_bytes")
        self._m_ticks_pull = monitor.gauge("serving.ticks_per_pull")
        self._m_ticks_pull.set(self.mt_k)
        self._m_spill = monitor.counter("serving.host_spills")
        self._m_swapin = monitor.counter("serving.host_swapins")
        # speculative-decode surface (stay 0 with spec off): proposed =
        # gamma per greedy slot per tick, accepted = drafts the verify
        # kept; the rate gauge is THIS ENGINE's cumulative
        # accepted/proposed (the counters are process-global)
        self._m_spec_prop = monitor.counter("serving.spec_proposed")
        self._m_spec_acc = monitor.counter("serving.spec_accepted")
        self._m_spec_rate = monitor.gauge("serving.spec_accept_rate")
        self._spec_prop_total = 0
        self._spec_acc_total = 0
        # weight-only quant surface (stays 0/unset with quant off):
        # the bytes gauges report THIS engine's weight tree before and
        # after the int8 rewrite (the HBM halving observable); the
        # counter advances by the number of fused dequant-matmuls each
        # device pass executes (per_layer quantized leaves x depth +
        # the head — a full pass per decode tick / prefill chunk, plus
        # gamma truncated draft passes per spec tick)
        self._m_qw = monitor.gauge("serving.quant_weights_bytes")
        self._m_fpw = monitor.gauge("serving.fp_weights_bytes")
        self._m_qmm = monitor.counter("serving.quant_matmuls")
        self._qmm_full = self._qmm_draft = 0
        if self._quant_info:
            self._m_qw.set(self._quant_info["quant_bytes"])
            self._m_fpw.set(self._quant_info["fp_bytes"])
            self._qmm_full = (self._quant_info["per_layer"] * n_layers
                              + self._quant_info["head"])
            self._qmm_draft = (self._quant_info["per_layer"]
                               * self.spec_draft_layers
                               + self._quant_info["head"])

    # -------------------------------------------------------- page pool
    def _init_paged_cache(self):
        """The paged pool buffers: {"k","v": [L, P, page_size, KV, hd]}
        in the family's cache dtype (probed shape-only via eval_shape —
        no dense allocation) + the device page table "pt"."""
        probe = jax.eval_shape(
            lambda: self.family.init_cache(self.cfg, 1, 1))
        shp = probe["k"].shape                 # [L, 1, 1, KV, hd]
        pages = (shp[0], self.num_pages, self.page_size) + shp[3:]
        return {"k": jnp.zeros(pages, probe["k"].dtype),
                "v": jnp.zeros(pages, probe["v"].dtype),
                "pt": jnp.asarray(self._ptab)}

    # --------------------------------------------- tensor-parallel seams
    def _rep(self, x, dtype=None):
        """Upload one host value to the device(s): plain jnp.asarray on
        a single-device engine; REPLICATED over the serving mesh under
        mesh= (a committed single-device array mixed into a sharded jit
        would be a placement error). Every host->device upload in the
        engine routes here, so the tick's inputs are mesh-consistent by
        construction."""
        if self._rep_sharding is None:
            return (jnp.asarray(x) if dtype is None
                    else jnp.asarray(x, dtype))
        a = np.asarray(x, dtype) if dtype is not None else np.asarray(x)
        return jax.device_put(a, self._rep_sharding)

    def _shard_params(self, params):
        """device_put the param tree per the family's module-level
        SERVING_PARAM_SPECS (heads/ffn column-row split on the tp
        axis, embeddings vocab-parallel, norms replicated); leaves the
        table doesn't name — and dims the tp degree doesn't divide —
        replicate (parallel.mesh.sharding_for's shape-aware degrade)."""
        from jax.sharding import PartitionSpec
        from ..parallel.mesh import sharding_for
        specs = self._serving_specs or {}
        return {name: jax.device_put(
                    v, sharding_for(specs.get(name, PartitionSpec()),
                                    self.mesh, shape=np.shape(v)))
                for name, v in params.items()}

    def _new_cache(self):
        """Allocate the pool cache (dense slot pool or paged block
        pool), sharded over the serving mesh when one is set — the KV-
        head axis per kernels/decode_attention.cache_pspecs, the page
        table replicated. Shared by __init__ and _hard_reset so a
        recovery reallocation can never come back with a different
        layout (the jitted tick would silently recompile). Under mesh=
        the pool is born sharded — jit with out_shardings, shapes from
        eval_shape — so no device ever holds the WHOLE pool, even
        transiently: the point of tp is a KV pool bigger than one
        chip's HBM, and a full-pool staging allocation would OOM at
        construction exactly when tp matters. (Params take the same
        no-staging path for free: _shard_params device_puts the host
        tree straight to its NamedShardings.)"""
        def mk():
            if self.paged:
                return self._init_paged_cache()
            return self.family.init_cache(self.cfg, self.num_slots,
                                          self.max_len)
        if self.mesh is None:
            return mk()
        if self._cache_pin is None:
            from ..kernels.decode_attention import cache_pspecs
            from ..parallel.mesh import sharding_for
            from jax.sharding import PartitionSpec
            specs = cache_pspecs(self.paged, self.tp_axis)
            shapes = jax.eval_shape(mk)
            self._cache_pin = {
                k: sharding_for(specs.get(k, PartitionSpec()),
                                self.mesh, shape=v.shape)
                for k, v in shapes.items()}
        return jax.jit(mk, out_shardings=self._cache_pin)()

    def _build_decode(self, spec: bool):
        """The decode-tick jit for `spec` drafts on or off, cached per
        flag in `_decode_variants` (reset by _make_executables on mesh
        rebuild). Four bodies: multi-tick x spec crossed — all share
        the donation/static signature, so `_decode_guarded` only varies
        its ARG assembly (keyed off self.spec / self.mt_k)."""
        cached = self._decode_variants.get(bool(spec))
        if cached is not None:
            return cached
        run_cfg = self._run_cfg
        _oor = (self.max_pages * self.page_size if self.paged else None)
        if self.mt_k > 1 and spec:
            from .multi_tick import multi_tick_spec_scan
            fn = jax.jit(
                functools.partial(multi_tick_spec_scan,
                                  fwd=self.family.forward_cached,
                                  cfg=run_cfg, max_top_k=self.max_top_k,
                                  guard=self.guardrails,
                                  gamma=self.spec_gamma,
                                  draft_layers=self.spec_draft_layers,
                                  k_ticks=self.mt_k,
                                  max_len=self.max_len,
                                  oor_pos=_oor,
                                  cache_pin=self._cache_pin,
                                  tele=self._tick_tele),
                donate_argnums=(1, 2), static_argnames=("sampling",))
        elif self.mt_k > 1:
            from .multi_tick import multi_tick_scan
            fn = jax.jit(
                functools.partial(multi_tick_scan,
                                  fwd=self.family.forward_cached,
                                  cfg=run_cfg, max_top_k=self.max_top_k,
                                  guard=self.guardrails,
                                  k_ticks=self.mt_k,
                                  max_len=self.max_len,
                                  oor_pos=_oor,
                                  cache_pin=self._cache_pin,
                                  tele=self._tick_tele),
                donate_argnums=(1, 2), static_argnames=("sampling",))
        elif spec:
            from .spec_decode import spec_tick
            fn = jax.jit(
                functools.partial(spec_tick,
                                  fwd=self.family.forward_cached,
                                  cfg=run_cfg, max_top_k=self.max_top_k,
                                  guard=self.guardrails,
                                  gamma=self.spec_gamma,
                                  draft_layers=self.spec_draft_layers,
                                  oor_pos=_oor,
                                  cache_pin=self._cache_pin,
                                  tele=self._tick_tele),
                donate_argnums=(1, 2), static_argnames=("sampling",))
        else:
            fn = jax.jit(
                functools.partial(_decode_tick,
                                  fwd=self.family.forward_cached,
                                  cfg=run_cfg, max_top_k=self.max_top_k,
                                  guard=self.guardrails, oor_pos=_oor,
                                  cache_pin=self._cache_pin,
                                  tele=self._tick_tele),
                donate_argnums=(1, 2), static_argnames=("sampling",))
        self._decode_variants[bool(spec)] = fn
        return fn

    def set_spec_drafts(self, enabled: bool) -> bool:
        """Toggle speculative-decode drafts live (the brownout ladder's
        level-1 lever): flipping OFF swaps the decode jit to the plain
        tick — drafts burn extra FLOPs for latency, and greedy streams
        are bit-identical with or without them, so the switch frees
        capacity with nothing user-visible. Only an engine BUILT with
        spec on can re-enable (`enabled=True` is a no-op otherwise);
        the first flip in each direction compiles the other variant
        once (a warmup-class recompile — the zero-recompile invariant
        counts steady-state ticks, and each variant's trace cache
        persists across later flips). Returns the live spec flag."""
        want = bool(enabled) and self._spec_capable
        if want == self.spec:
            return self.spec
        self.spec = want
        self._tick_span = self.mt_k * ((self.spec_gamma + 1) if want
                                       else 1)
        self._decode = self._build_decode(want)
        return self.spec

    def _make_executables(self) -> None:
        """Build (or REBUILD) the jitted bodies — decode tick, bucketed/
        chunked prefill, COW page copy — from the engine's current mesh
        state. Extracted from __init__ so `rebuild_on_mesh` (preemption
        recovery) can re-jit on the surviving mesh: the partials close
        over `self._cache_pin`, which a mesh change invalidates. Must
        run AFTER `_new_cache` has pinned the cache layout (the pin
        dict is closed over by identity). Fresh jits start with empty
        trace caches — one warmup recompile per body, then the
        trace-count ceilings hold exactly as at first construction."""
        run_cfg = self._run_cfg
        self._repin = None      # lazy identity re-pin (see _pin_cache_host)
        # the decode jit is keyed by the LIVE spec flag: brownout's
        # set_spec_drafts swaps between the spec and non-spec variants
        # without touching prefill/COW, and a mesh rebuild resets the
        # cache (the partials close over a pin the new mesh invalidates)
        self._decode_variants = {}
        self._decode = self._build_decode(self.spec)
        if self.paged:
            self._prefill = jax.jit(
                functools.partial(_prefill_chunk,
                                  fwd=self.family.forward_cached,
                                  cfg=run_cfg, max_top_k=self.max_top_k,
                                  guard=self.guardrails,
                                  cache_pin=self._cache_pin),
                donate_argnums=(1,), static_argnames=("sampling",))
            self._cow = jax.jit(
                functools.partial(_cow_copy,
                                  cache_pin=self._cache_pin),
                donate_argnums=(0,))
        else:
            self._prefill = jax.jit(
                functools.partial(_prefill_slot,
                                  fwd=self.family.forward_cached,
                                  init_cache=self.family.init_cache,
                                  cfg=run_cfg, max_top_k=self.max_top_k,
                                  guard=self.guardrails,
                                  cache_pin=self._cache_pin),
                donate_argnums=(1,), static_argnames=("sampling",))

    def pool_stats(self) -> dict:
        """The kv-pool observable (paged layout only): page states,
        shared/COW/chunk counters, and the HBM the pool holds vs what
        the dense layout would."""
        if not self.paged:
            return {"layout": "dense"}
        st = self._pool.stats()
        st["layout"] = "paged"
        st["cow_copies"] = self._m_cow.value
        st["prefill_chunks"] = self._m_chunks.value
        if self._host_tier is not None:
            st["host_tier"] = self._host_tier.stats()
        return st

    def quant_stats(self) -> dict:
        """The weight-only quant observable: fp vs int8 weight bytes
        and the per-pass fused-matmul counts (quantization/serving.py
        info dict), or {"quant": "off"}."""
        if not self._quant_info:
            return {"quant": "off"}
        return {"quant": "int8", **self._quant_info}

    def _publish_pool_gauges(self) -> None:
        if not self.paged:
            return
        pages = int((self._pool.ref[1:] > 0).sum())
        self._m_pages.set(pages)
        self._m_shared.set(int((self._pool.ref[1:] > 1).sum()))
        self._m_kv_bytes.set(pages * self._page_bytes)

    def _publish_tier_gauges(self) -> None:
        """Disaggregation gauges: host-side bookkeeping only (zero
        extra device pulls), published on the telemetry FLUSH cadence
        (ServingTelemetry on_flush=) and with the per-step pool gauges.
        The spill/swap-in COUNTERS advance at event time instead
        (_spill_page / _admit_paged) — process-global counters can't
        take last-writer deltas with several engines alive."""
        self._m_ticks_pull.set(self.mt_k)
        if self._host_tier is not None:
            self._m_kv_host.set(self._host_tier.bytes)

    # ---------------------------------------------- host-tier KV offload
    def _spill_page(self, pid: int, key) -> None:
        """_PagePool.on_evict tap: demote the evicting registered page
        to the host tier before its prefix-map entry drops. The page is
        FROZEN (registered => COW-immutable), so the copy taken here is
        bit-identical to what a device hit would read; the engine is
        single-threaded, so the pool never evicts mid-write. Skips keys
        the tier already holds (a page that round-tripped host ->
        device -> eviction again)."""
        if self._host_tier is None or key in self._host_tier:
            return
        k_np = np.asarray(self._cache["k"][:, pid])
        v_np = np.asarray(self._cache["v"][:, pid])
        if self._host_tier.put(key, k_np, v_np):
            self._m_spill.add()

    def _prefetch_host(self, req: "Request") -> None:
        """Asynchronous swap-in ahead of admission: while the head-of-
        line request WAITS for capacity, start `jax.device_put` uploads
        of the host-tier pages its prefix walk will hit, so by the time
        `_admit_paged` maps them the transfers have overlapped the
        wait. Staged uploads park in `_host_stage` (key -> (dk, dv))
        and are consumed (or dropped) by the next admission of that
        key; idempotent per key."""
        if self._host_tier is None or not self.prefix_sharing:
            return
        ps = self.page_size
        toks = req.prompt
        for j in range(len(toks) // ps):
            key = _prefix_key(toks, (j + 1) * ps)
            if key in self._host_stage or key in self._pool.by_key:
                continue
            pair = self._host_tier.get(key)
            if pair is None:
                break        # tier walk stops at the first miss too
            self._host_stage[key] = (self._rep(pair[0]),
                                     self._rep(pair[1]))

    # ------------------------------------------------- memory observability
    def memory_ledger(self) -> dict:
        """This engine's `cost_model.serving_memory_ledger` — per-chip
        HBM attribution (weights / quantized pairs / kv pool / decode
        scratch) from the LIVE configuration. The analytical half that
        `profiler.mem_audit.audit_serving_memory` diffs against the
        compiled decode tick, and the first page of an oom_forensics
        dump."""
        from ..cost_model import jnp_dtype_bytes, serving_memory_ledger
        return serving_memory_ledger(
            self.cfg, family=self.family.name,
            layout="paged" if self.paged else "dense",
            quant="int8" if self._quant_info else "off",
            num_slots=self.num_slots, max_len=self.max_len,
            page_size=self.page_size,
            num_pages=self.num_pages if self.paged else 0,
            cache_bytes_per_elem=int(self._cache["k"].dtype.itemsize),
            dtype_bytes=jnp_dtype_bytes(getattr(self.cfg, "dtype", None)),
            tp=self.tp,
            host_kv_bytes=(int(self._host_tier.bytes)
                           if self._host_tier is not None else 0))

    def compiled_memory_stats(self, sampling: bool = False) -> dict:
        """XLA's compiled memory accounting for THIS engine's decode
        tick: re-lower `self._decode` over the avals of the live state
        (shapes/dtypes only — no tick dispatched, no host pull, no
        device transfer) and read `memory_analysis()` through the
        profiler.mem_audit seam. The jit's trace cache makes the
        compile a warm no-op when the tick already ran with the same
        sampling mode."""
        from ..profiler.mem_audit import compiled_memory_stats
        aval = lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype)  # noqa: E731
        cache = jax.tree_util.tree_map(aval, self._cache)
        if self.paged and "pt" not in cache:
            cache["pt"] = jax.ShapeDtypeStruct(
                self._ptab.shape, self._ptab.dtype)
        # the tick's dstate tuple, aval'd from the HOST mirrors so a
        # dirty (not-yet-replicated) state needs no device round-trip
        dstate = tuple(jax.ShapeDtypeStruct(m.shape, m.dtype)
                       for m in (self._cur_tok, self._positions,
                                 self._active, self._temps,
                                 self._top_ks, self._req_ids,
                                 self._gen_idx))
        args = [jax.tree_util.tree_map(aval, self._params), cache,
                dstate, aval(self._base_key), aval(self._poison_ones)]
        if self.spec:
            args.append(aval(self._poison_ones))
        if self.mt_k > 1:
            args += [jax.ShapeDtypeStruct(self._eos_ids.shape,
                                          self._eos_ids.dtype),
                     jax.ShapeDtypeStruct(self._max_new.shape,
                                          self._max_new.dtype)]
        compiled = self._decode.lower(
            *args, sampling=bool(sampling)).compile()
        return compiled_memory_stats(compiled)

    def _dump_oom_forensics(self, where: str, exc) -> None:
        """The OOM black box: when a dispatch seam sees
        RESOURCE_EXHAUSTED, dump ledger + live-array census (summarized
        by shape/dtype/sharding, byte-sorted) + pool/quant stats +
        active config to the flight dir BEFORE the retry/reset
        machinery runs, so the post-mortem names the tenant instead of
        guessing. Forensics must never mask the original failure —
        every step is best-effort."""
        try:
            from ..profiler.mem_audit import live_array_census
            census = live_array_census()
            self._m_oom.add()
            self._flight.configure(oom_forensics={
                "where": where, "tick": self._ticks,
                "error": repr(exc)[:500],
                "ledger": self.memory_ledger(),
                "census": census["rows"],
                "live_bytes": census["total_bytes"],
                "pool": self.pool_stats(), "quant": self.quant_stats(),
                "config": {"layout": "paged" if self.paged else "dense",
                           "num_slots": self.num_slots,
                           "max_len": self.max_len, "tp": self.tp}})
            self._flight.note(oom_forensics=where, tick=self._ticks)
            self._flight.dump("oom_forensics")
        except Exception:                      # noqa: BLE001
            pass

    # ------------------------------------------------------- observables
    def trace_counts(self):
        """(decode traces, prefill traces) — the zero-recompile
        acceptance observable: decode holds at one trace per sampling
        mode (<= 2 forever); prefill grows only with NEW (prompt
        bucket, sampling mode) pairs — ceiling 2·log2(max_len)."""
        return self._decode._cache_size(), self._prefill._cache_size()

    def tick_records(self) -> list:
        """The in-tick telemetry ring (profiler/serving_telemetry
        serving_tick / serving_prefill records, newest-last); empty
        with telemetry off. tools/serving_attrib.py joins these with
        the cost-model ledger."""
        return [] if self._tick_log is None else self._tick_log.records()

    def flush_telemetry(self, timeout: Optional[float] = None) -> None:
        """Block until every pending serving_tick record is on disk
        (no-op without telemetry_jsonl=)."""
        if self._tick_log is not None:
            self._tick_log.flush(timeout=timeout)

    def has_work(self) -> bool:
        # a slot mid-chunked-prefill holds a request but is not yet
        # active for decode — still work
        return (bool(self._queue) or bool(self._active.any())
                or any(r is not None for r in self._slot_req))

    @property
    def active_requests(self):
        return [r for r in self._slot_req if r is not None]

    # --------------------------------------------------------- admission
    def submit(self, prompt, max_new_tokens: int, temperature: float = 0.0,
               top_k: int = 0, eos_id: Optional[int] = None,
               deadline_s: Optional[float] = None,
               deadline_ticks: Optional[int] = None,
               tenant: str = "default", priority: int = 0,
               _trace=None) -> Request:
        """Queue one request. prompt: 1-D int token ids. Returns the
        live Request; its .tokens fills in as the engine steps.
        `deadline_s` / `deadline_ticks` bound the request's TOTAL
        lifetime (queue wait included) in wall seconds / engine ticks —
        exceeding either resolves it with finish_reason "timeout".
        Raises BackpressureError when the queue is at max_queue under
        the "reject" policy; under "shed_oldest" the oldest queued
        request is evicted to make room. `_trace` lets a router thread
        ITS RequestTrace through so a dispatched (or replayed) request
        keeps one span tree; with tracing=True and no _trace the
        engine mints its own."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        t0 = prompt.shape[0]
        if t0 < 1:
            raise ValueError("empty prompt")
        if max_new_tokens < 1:
            raise ValueError(f"max_new_tokens must be >= 1; "
                             f"got {max_new_tokens}")
        if t0 + max_new_tokens > self.max_len:
            raise ValueError(
                f"prompt ({t0}) + max_new_tokens ({max_new_tokens}) "
                f"exceeds the engine's max_len ({self.max_len})")
        if top_k > 0 and self.max_top_k <= 0:
            raise ValueError(
                "engine was built with max_top_k=0 (greedy/temperature "
                "only); rebuild with max_top_k >= the largest top_k "
                "you will request")
        if top_k > self.max_top_k:
            raise ValueError(f"top_k={top_k} exceeds the engine's "
                             f"static max_top_k={self.max_top_k}")
        if self.paged:
            need = self._pages_needed(t0, max_new_tokens)
            if need > self.num_pages - 1:
                raise PoolExhaustedError(
                    f"request needs {need} pages worst-case but the "
                    f"pool holds {self.num_pages - 1} allocatable "
                    f"pages (page_size={self.page_size})",
                    pages_needed=need, pages_total=self.num_pages - 1)
        if self.max_queue > 0 and len(self._queue) >= self.max_queue:
            if self.queue_policy == "shed_oldest":
                self._finish(self._queue.popleft(), "evicted")
            else:
                self._m_rej.add()
                raise BackpressureError(
                    f"admission queue full ({len(self._queue)} waiting, "
                    f"max_queue={self.max_queue})",
                    queue_depth=len(self._queue))
        req = Request(self._next_id, prompt, int(max_new_tokens),
                      float(temperature), int(top_k), eos_id,
                      deadline_s=(None if deadline_s is None
                                  else float(deadline_s)),
                      deadline_ticks=(None if deadline_ticks is None
                                      else int(deadline_ticks)),
                      tenant=tenant, priority=priority)
        req.t_submit = time.perf_counter()
        req._tick_submit = self._ticks
        req._engine = self
        if _trace is not None:
            req.trace = _trace
        elif self._tracer is not None:
            req.trace = self._tracer.trace(
                f"request-{req.id}", request_id=req.id,
                prompt_len=t0, max_new_tokens=int(max_new_tokens))
        if req.trace is not None:
            req._sp_queue = req.trace.begin(
                "queue", queue_depth=len(self._queue),
                attempt=req.trace.attempt)
        self._next_id += 1
        self._queue.append(req)
        self._m_sub.add()
        self._m_queue.set(len(self._queue))
        return req

    # --------------------------------------------------------- the tick
    def step(self):
        """One engine tick: expire queued requests past their TTL or
        deadline, advance ONE mid-prefill slot by one chunk (the
        chunked-prefill interleave), admit queued requests into free
        slots (reserving their worst-case page need first under the
        paged layout — a request that cannot reserve stays queued),
        advance all active slots one token through the single jitted
        decode step (quarantining poisoned rows), then enforce
        deadlines on the survivors. Returns this tick's
        (request, token) emissions in slot order."""
        events: List[tuple] = []
        actions = {}
        if _FAULT_HOOK is not None:
            actions = _FAULT_HOOK(self._ticks) or {}
        if self.paged and actions.pop("raise_cow", None):
            self._raise_cow = True
        if actions.pop("raise_migrate", None):
            self._raise_migrate = True       # next snapshot raises once
        now = time.perf_counter()
        self._expire_queued(now)
        if self.paged:
            self._advance_prefill(events, actions)
        while self._queue:
            slot = self._free_slot()
            if slot is None:
                break
            head = self._queue[0]
            if self._deadline_expired(head, now):
                self._queue.popleft()
                self._finish(head, "timeout")
                continue
            if (self.paged
                    and self._plan_admission(head)[4]
                    > self._pool.available()):
                # overlap the wait: start device_put uploads of the
                # host-tier pages this head's prefix walk will hit, so
                # admission maps already-transferred buffers
                self._prefetch_host(head)
                break       # head-of-line waits for pages (FCFS); live
                #             slots free pages as they finish
            self._queue.popleft()
            self._admit_guarded(slot, head, events, actions)

        if self._active.any():
            self._decode_guarded(events, actions)
        # outside the decode branch: a slot mid-chunked-prefill must
        # honor its deadline even when no stream is decoding yet
        self._enforce_deadlines(time.perf_counter())

        self._ticks += 1
        self._m_occ.set(int(self._active.sum()))
        self._m_queue.set(len(self._queue))
        self._publish_pool_gauges()
        self._publish_tier_gauges()
        return events

    def drain(self, max_ticks: Optional[int] = None):
        """Step until idle (or max_ticks); returns all emissions.
        NOTE: with max_ticks the engine may still hold live requests on
        return — call `abort_pending()` (or use `generate(...,
        max_ticks=)`, which does) when partial delivery must still
        resolve every request."""
        events = []
        ticks = 0
        while self.has_work():
            events.extend(self.step())
            ticks += 1
            if max_ticks is not None and ticks >= max_ticks:
                break
        return events

    def abort_pending(self, reason: str = "evicted") -> int:
        """Resolve EVERY live request (queued and in-slot) with the
        terminal `reason` — after this no request is in limbo. Returns
        the number aborted."""
        if reason not in TERMINAL_REASONS:
            raise ValueError(f"reason {reason!r} not in "
                             f"{sorted(TERMINAL_REASONS)}")
        n = 0
        while self._queue:
            self._finish(self._queue.popleft(), reason)
            n += 1
        for req in list(self._slot_req):
            if req is not None:
                self._finish(req, reason)
                n += 1
        self._m_occ.set(int(self._active.sum()))
        self._m_queue.set(len(self._queue))
        return n

    def generate(self, prompts: Sequence, max_new_tokens: int,
                 temperature: float = 0.0, top_k: int = 0,
                 eos_id: Optional[int] = None,
                 deadline_s: Optional[float] = None,
                 deadline_ticks: Optional[int] = None,
                 max_ticks: Optional[int] = None) -> List[np.ndarray]:
        """Batch convenience: submit every prompt, drain, return each
        request's generated ids (submission order). Never returns with
        a request in limbo: whatever `max_ticks` (or a deadline) left
        undelivered is resolved with a terminal finish_reason
        ("evicted") before returning, so `.done` is True for every
        request this call submitted."""
        reqs = [self.submit(p, max_new_tokens, temperature=temperature,
                            top_k=top_k, eos_id=eos_id,
                            deadline_s=deadline_s,
                            deadline_ticks=deadline_ticks)
                for p in prompts]
        self.drain(max_ticks)
        for r in reqs:
            if not r.done:
                if r.slot is None:
                    try:
                        self._queue.remove(r)
                    except ValueError:
                        pass
                self._finish(r, "evicted")
        self._m_queue.set(len(self._queue))
        return [np.asarray(r.tokens, np.int32) for r in reqs]

    # ------------------------------------------------------ terminality
    def _clear_slot(self, slot: int) -> None:
        """Return a slot to the free pool: registry, every host mirror,
        and the device-state dirty flag (the ONE place a slot's mirrors
        reset — _finish and _rollback_slot both route here). Under the
        paged layout this is also where the slot's pages release:
        refcounts drop, registered pages park in the LRU cache, the
        table row snaps back to scratch, and any un-spent admission
        reservation returns to the pool."""
        self._slot_req[slot] = None
        self._active[slot] = False
        self._positions[slot] = 0
        self._cur_tok[slot] = 0
        self._temps[slot] = 0.0
        self._top_ks[slot] = 0
        self._gen_idx[slot] = 0
        self._eos_ids[slot] = -1
        self._max_new[slot] = 0
        self._dirty = True
        if self.paged:
            row = self._ptab[slot]
            for j in np.nonzero(row)[0]:
                self._pool.release(int(row[j]))
            row[:] = 0
            self._pool.reserved -= int(self._slot_reserve[slot])
            self._slot_reserve[slot] = 0
            self._pt_dirty = True
            try:
                self._prefilling.remove(slot)
            except ValueError:
                pass

    def _finish(self, req: Request, reason: str) -> None:
        """THE terminal transition: exactly-once by construction (a
        resolved request is never re-finished), frees the slot and
        dirties the device mirror when the request was mid-decode."""
        if req.done:
            return
        if req.slot is not None:
            self._clear_slot(req.slot)
        req.slot = None
        req.done = True
        req.finish_reason = reason
        if req.trace is not None:
            # the ONE terminal span — exactly-once because _finish is
            # the one terminal seam AND RequestTrace.finish is once-
            # only (a router's own _finish then no-ops)
            req.trace.finish(reason, tokens=len(req.tokens))
        self._m_done.add()
        ctr = self._reason_ctr.get(reason)
        if ctr is not None:
            ctr.add()

    def cancel(self, req: Request) -> bool:
        """Resolve `req` with finish_reason "cancelled" right now:
        dequeues a waiting request, frees the slot of a mid-decode one.
        Returns False when it already resolved."""
        if req.done:
            return False
        if req.slot is None:
            try:
                self._queue.remove(req)
            except ValueError:
                pass                   # not ours / already dequeued
        self._finish(req, "cancelled")
        self._m_queue.set(len(self._queue))
        return True

    # -------------------------------------------------------- deadlines
    def _deadline_expired(self, req: Request, now: float) -> bool:
        if (req.deadline_s is not None
                and now - req.t_submit >= req.deadline_s):
            return True
        if (req.deadline_ticks is not None
                and self._ticks - req._tick_submit >= req.deadline_ticks):
            return True
        return False

    def _expire_queued(self, now: float) -> None:
        if not self._queue:
            return
        keep: collections.deque = collections.deque()
        for req in self._queue:
            ttl_hit = (self.queue_ttl_s > 0.0
                       and now - req.t_submit >= self.queue_ttl_s)
            if ttl_hit or self._deadline_expired(req, now):
                self._finish(req, "timeout")
            else:
                keep.append(req)
        self._queue = keep

    def _enforce_deadlines(self, now: float) -> None:
        for req in list(self._slot_req):
            if req is not None and self._deadline_expired(req, now):
                self._finish(req, "timeout")

    # ----------------------------------------------- self-healing calls
    def _on_fault(self, kind: str, exc: BaseException) -> None:
        """Every serving fault leaves a black box (no-op without
        $PADDLE_TPU_FLIGHT_DIR) and a counter bump."""
        self._m_fault.add()
        self._flight.configure(last_serving_fault=f"{kind}: {exc}")
        self._flight.note(serving_fault=kind, tick=self._ticks,
                          error=str(exc))
        self._flight.dump(f"serving_{kind}_fault")
        print(f"[serving] {kind} fault at tick {self._ticks}: {exc}",
              file=sys.stderr, flush=True)

    def _backoff(self, attempt: int) -> None:
        self._m_retry.add()
        time.sleep(min(self.backoff_base * (2.0 ** attempt),
                       self.backoff_max))

    def _rollback_slot(self, slot: int, req: Request, n_tok: int) -> None:
        """Undo a partially-applied admission: host mirrors, the slot
        registry (and under the paged layout the slot's pages and
        reservation) and the request's token list return to their
        pre-admit state, and the device mirror is marked stale."""
        self._clear_slot(slot)
        req.slot = None
        req._pf_next = None
        req.shared_tokens = 0
        del req.tokens[n_tok:]

    def _cache_dead(self) -> bool:
        """True when the pool cache's buffers were consumed by a FAILED
        donated dispatch (execution died after donation — possible on a
        real accelerator; CPU ignores donation): re-dispatching would
        only raise 'array deleted', so the caller must hard-reset."""
        try:
            return any(getattr(leaf, "is_deleted", lambda: False)()
                       for leaf in jax.tree_util.tree_leaves(self._cache))
        except Exception:                          # noqa: BLE001
            return False

    def _hard_reset(self, reason: str) -> None:
        """Last-resort recovery after an exhausted retry budget or a
        hung pull (re-dispatching donated buffers is illegal): every
        in-flight request terminates as "evicted" and the pool cache is
        reallocated; queued requests stay queued — if the fault was
        transient they admit cleanly into the fresh pool."""
        for req in list(self._slot_req):
            if req is not None:
                self._finish(req, "evicted")
        if self.paged:
            # prefix-map contents died with the buffers: fresh pool.
            # The host tier SURVIVES (its pages are deterministic
            # functions of prompt + params, still bit-valid) — only
            # the eviction tap re-attaches
            self._pool = _PagePool(self.num_pages, self.page_size)
            if self._host_tier is not None:
                self._pool.on_evict = self._spill_page
            self._ptab[:] = 0
            self._slot_reserve[:] = 0
            self._prefilling.clear()
            self._pt_dirty = False
        self._cache = self._new_cache()
        self._dstate = None
        self._dirty = True
        self._flight.configure(last_serving_fault=f"hard_reset: {reason}")
        self._flight.dump("serving_hard_reset")
        print(f"[serving] hard reset at tick {self._ticks} ({reason}): "
              f"pool cache reallocated", file=sys.stderr, flush=True)

    def _pull(self, value, stall_s: float = 0.0):
        """The one device->host pull, optionally under the resilience
        watchdog (re-polls the SAME future with backoff — donated
        buffers cannot be re-dispatched). The persistent WatchdogPuller
        is the ~2 ms-tick-rate variant of the trainer's per-step pull
        thread. `value` may be a TUPLE of device arrays (the tick's
        token array + the in-tick telemetry row): the pair fetches in
        this ONE call, so the pull count the invariant tests wrap stays
        one per tick with telemetry on. `stall_s` is the injected
        tick_stall: it sleeps INSIDE the watchdog-monitored pull so the
        drill exercises the real budget/backoff path."""
        def src():
            if stall_s > 0.0:
                time.sleep(stall_s)
            if isinstance(value, tuple):
                return tuple(np.asarray(v)
                             for v in jax.device_get(list(value)))
            return np.asarray(value)
        if self.watchdog_timeout > 0.0:
            if self._puller is None:
                from ..parallel.resilience import WatchdogPuller
                self._puller = WatchdogPuller(label="serving tick")
            return self._puller.pull(
                src, self.watchdog_timeout, self.retries,
                self.backoff_base, self.backoff_max,
                on_retry=self._on_stall_retry)
        return src()

    def _on_stall_retry(self, attempt: int) -> None:
        """Watchdog backoff observer: count it, and leave a black box
        on the FIRST stall of a tick — a pull that needed backoff is
        the tunnel-flap post-mortem case even when it recovers."""
        self._m_retry.add()
        self._flight.note(serving_stall_attempt=attempt,
                          tick=self._ticks)
        if attempt == 0:
            self._flight.dump("serving_stall")

    def _admit_guarded(self, slot: int, req: Request, events: list,
                       actions: dict) -> None:
        """Admission under the fault guard: a raising prefill rolls the
        slot back and retries with backoff; an exhausted budget resolves
        the request as "evicted" (never limbo). A hung pull or a cache
        lost to a failed donated dispatch is NOT retryable — re-waiting
        the watchdog budget / re-dispatching deleted buffers can only
        fail again — and escalates to `_hard_reset` like the tick's."""
        n_tok = len(req.tokens)
        from ..parallel.resilience import StepHungError
        for attempt in range(self.retries + 1):
            try:
                if actions.pop("raise_prefill", None):
                    raise ServingFaultError("injected prefill fault")
                self._admit(slot, req, events)
                return
            except StepHungError as e:
                self._rollback_slot(slot, req, n_tok)
                self._on_fault("prefill_hang", e)
                self._finish(req, "evicted")
                self._hard_reset("prefill watchdog hang")
                return
            except Exception as e:                 # noqa: BLE001
                self._rollback_slot(slot, req, n_tok)
                if "RESOURCE_EXHAUSTED" in str(e):
                    self._dump_oom_forensics("prefill", e)
                self._on_fault("prefill", e)
                dead = self._cache_dead()
                if dead or attempt >= self.retries:
                    self._finish(req, "evicted")
                    if dead:
                        self._hard_reset("prefill lost the donated cache")
                    return
                self._backoff(attempt)

    def _decode_guarded(self, events: list, actions: dict) -> None:
        """One decode tick under the fault guard. Mirrors advance only
        after a successful pull, so a failed attempt resyncs `_dstate`
        from them and re-runs the tick idempotently (same state -> same
        KV writes). A hung pull or exhausted budget hard-resets."""
        poison_slot = actions.pop("poison_slot", None)
        draft_slot = actions.pop("draft_poison_slot", None)
        stall_s = actions.pop("stall_s", 0.0)
        from ..parallel.resilience import StepHungError
        for attempt in range(self.retries + 1):
            try:
                if actions.pop("raise_decode", None):
                    raise ServingFaultError("injected decode fault")
                if actions.pop("raise_oom", None):
                    # the injected message carries the real backend's
                    # marker so the forensics trigger below is the SAME
                    # path a true allocation failure takes
                    raise ServingFaultError(
                        "injected allocation failure: "
                        "RESOURCE_EXHAUSTED: simulated out of memory")
                if self.paged:
                    # every active slot's write page must exist and be
                    # private before the scatter (idempotent: a retry
                    # finds them already allocated)
                    self._prepare_tick_pages()
                    if self._pt_dirty:
                        self._cache["pt"] = self._rep(self._ptab)
                        self._pt_dirty = False
                if self._dirty:
                    self._dstate = (
                        self._rep(self._cur_tok),
                        self._rep(self._positions),
                        self._rep(self._active),
                        self._rep(self._temps),
                        self._rep(self._top_ks),
                        self._rep(self._req_ids),
                        self._rep(self._gen_idx))
                    if self.mt_k > 1:
                        # the scan's early-exit inputs ride the same
                        # dirty-rebuild cadence as the state tuple
                        self._daux = (self._rep(self._eos_ids),
                                      self._rep(self._max_new))
                    self._dirty = False
                sampling = bool(np.any(self._temps[self._active] > 0.0))
                poison = self._poison_ones
                if poison_slot is not None and self.guardrails:
                    p = np.ones(self.num_slots, np.float32)
                    p[int(poison_slot) % self.num_slots] = np.nan
                    poison = self._rep(p)
                poison_slot = None        # injected at most once
                t_dev0 = time.perf_counter()
                with RecordEvent("serving.decode_tick"):
                    if self.spec:
                        dpoison = self._poison_ones
                        if draft_slot is not None:
                            dp = np.ones(self.num_slots, np.float32)
                            dp[int(draft_slot) % self.num_slots] = np.nan
                            dpoison = self._rep(dp)
                        draft_slot = None     # injected at most once
                        args = (self._params, self._cache, self._dstate,
                                self._base_key, poison, dpoison)
                        if self.mt_k > 1:
                            args += self._daux
                        out = self._decode(*args, sampling=sampling)
                    else:
                        args = (self._params, self._cache, self._dstate,
                                self._base_key, poison)
                        if self.mt_k > 1:
                            args += self._daux
                        out = self._decode(*args, sampling=sampling)
                    # ONE host pull per tick ([N] non-spec; the
                    # [N, gamma+1] emission matrix under spec) — with
                    # in-tick telemetry the TICK_FIELDS row rides the
                    # SAME pull (a tuple fetch through the one _pull)
                    if self._tick_tele:
                        nxt, trow, self._cache, self._dstate = out
                        toks, tele_row = self._pull((nxt, trow), stall_s)
                    else:
                        nxt, self._cache, self._dstate = out
                        toks = self._pull(nxt, stall_s)
                        tele_row = None
                tick_ms = (time.perf_counter() - t_dev0) * 1e3
                stall_s = 0.0
                break
            except StepHungError as e:
                # the future may still land later; re-polling already
                # exhausted the budget and re-dispatch is illegal
                self._on_fault("decode_hang", e)
                self._hard_reset("watchdog hang")
                return
            except Exception as e:                 # noqa: BLE001
                self._dirty = True        # resync _dstate from mirrors
                if "RESOURCE_EXHAUSTED" in str(e):
                    self._dump_oom_forensics("decode", e)
                self._on_fault("decode", e)
                dead = self._cache_dead()
                if dead or attempt >= self.retries:
                    self._hard_reset("decode lost the donated cache"
                                     if dead else
                                     "decode retries exhausted")
                    return
                self._backoff(attempt)

        self._m_tick.add()
        if self._quant_info:
            self._m_qmm.add(self._qmm_full
                            + (self.spec_gamma * self._qmm_draft
                               if self.spec else 0))
        if self._tick_log is not None:
            host = {"queue_depth": len(self._queue)}
            if self.paged:
                host["prefilling"] = len(self._prefilling)
                host["pages_in_use"] = int((self._pool.ref[1:] > 0).sum())
            self._tick_log.record_tick(self._ticks, tele_row, host,
                                       tick_ms)
        tick_now = time.perf_counter()
        if self.spec:
            self._apply_spec_emissions(toks, events, tick_now)
            return
        if self.mt_k > 1:
            self._apply_multi_emissions(toks, events, tick_now)
            return
        for i in np.nonzero(self._active)[0]:
            req = self._slot_req[i]
            tok = int(toks[i])
            if tok < 0:
                # in-jit quarantine verdict: evict ONLY this slot; the
                # device state is stale (its row advanced) -> _finish
                # dirties it, co-batched rows rebuild from their clean
                # mirrors and stay bit-identical
                self._on_fault("poisoned", RuntimeError(
                    f"non-finite logits in slot {i} (request {req.id})"))
                self._finish(req, "poisoned")
                continue
            # mirror exactly what the tick did on device (positions
            # and gen_idx advanced under the active mask) — no
            # download, and the device state stays clean unless an
            # eviction dirties it
            self._emit_token(i, req, tok, events, tick_now)

    def _emit_token(self, i: int, req: Request, tok: int,
                    events: list, tick_now: float,
                    itl_ms: Optional[float] = None) -> None:
        """The per-token bookkeeping both decode paths share: advance
        the host mirrors (positions/_cur_tok/_gen_idx), record the
        token + SLO sample, and run the finish checks. The non-spec
        tick is the cut=1 case of the spec loop — one seam so a future
        accounting change can't silently miss one copy. `itl_ms`
        overrides the wall-clock inter-token sample: a multi-tick pull
        delivers K tokens at once, and attributing the whole dispatch
        gap to each would K-fold-inflate the ITL histogram — the
        caller amortizes the gap across the tokens it carried."""
        self._positions[i] += 1
        self._cur_tok[i] = tok
        self._gen_idx[i] += 1
        if req.trace is not None:
            req.trace.instant("decode.tick", parent=req._sp_decode,
                              tick=self._ticks, token=tok)
        req.tokens.append(tok)
        events.append((req, tok))
        self._m_tok.add()
        self._slo_itl.append((tick_now - req._t_last) * 1e3
                             if itl_ms is None else itl_ms)
        req._t_last = tick_now
        self._maybe_finish(req)

    def _apply_spec_emissions(self, toks, events: list,
                              tick_now: float) -> None:
        """Spec-mode post-pull bookkeeping: `toks` is the [N, gamma+1]
        emission matrix (column 0 = the always-emitted token or the -1
        quarantine sentinel; SPEC_PAD beyond the accepted prefix). The
        device advanced each active slot by its accepted count + 1;
        the mirrors advance identically UNLESS the request finishes
        mid-block (EOS / max_new_tokens inside the accepted prefix) —
        then _finish/_clear_slot dirties the device mirror, exactly
        the non-spec eviction path, and the unconsumed tail tokens are
        dropped (the non-spec engine would never have generated them).
        Under the paged layout, pages past every surviving slot's new
        position are speculative only and roll back to the pool."""
        from .spec_decode import SPEC_PAD
        width = self.spec_gamma + 1
        for i in np.nonzero(self._active)[0]:
            req = self._slot_req[i]
            flat = [int(t) for t in np.asarray(toks[i]).reshape(-1)]
            # the pull is `mt_k` blocks of gamma+1 columns (one block
            # under the single-dispatch spec tick); an all-PAD block
            # marks "retired in an earlier scan step" — stop there
            blocks = []
            for b in range(len(flat) // width):
                row = flat[b * width:(b + 1) * width]
                if b > 0 and row[0] == SPEC_PAD:
                    break       # dead block: the scan retired this slot
                if row[0] < -1:                  # defensive: never PAD
                    row[0] = -1
                blocks.append(row)
            poisoned = False
            emit: List[int] = []
            for row in blocks:
                if row[0] < 0:
                    poisoned = True
                    break
                cut = (row.index(SPEC_PAD) if SPEC_PAD in row
                       else len(row))
                if self._temps[i] <= 0.0:
                    # acceptance telemetry counts GREEDY slots only —
                    # sampled slots never propose
                    self._spec_prop_total += self.spec_gamma
                    self._spec_acc_total += cut - 1
                    self._m_spec_prop.add(self.spec_gamma)
                    self._m_spec_acc.add(cut - 1)
                emit.extend(row[:cut])
            if not blocks or (poisoned and not emit):
                self._on_fault("poisoned", RuntimeError(
                    f"non-finite logits in slot {i} (request {req.id})"))
                self._finish(req, "poisoned")
                continue
            # a multi-block pull amortizes the dispatch gap across the
            # tokens it carried (see _emit_token); the single-block
            # path keeps the wall-clock sample bit-for-bit as before
            share = ((tick_now - req._t_last) * 1e3 / max(len(emit), 1)
                     if len(blocks) > 1 else None)
            # mirror the device advance TOKEN BY TOKEN, not as one
            # block: _maybe_finish's cache-full eviction check reads
            # the position mirror, and advancing the whole block up
            # front would let `positions >= max_len` fire mid-block on
            # a boundary-legal request (prompt + max_new within gamma
            # of max_len), dropping accepted tokens the non-spec
            # engine would emit. A surviving slot's mirror still lands
            # exactly at the device's pos + cut; a mid-block finish
            # dirties the device state as before.
            for tok in emit:
                self._emit_token(i, req, tok, events, tick_now,
                                 itl_ms=share)
                if req.done:
                    break
            if poisoned and not req.done:
                # a later scan step hit the quarantine after this slot
                # already emitted real tokens this dispatch: deliver
                # them, then resolve exactly like the single-tick path
                self._on_fault("poisoned", RuntimeError(
                    f"non-finite logits in slot {i} (request {req.id})"))
                self._finish(req, "poisoned")
        if self._spec_prop_total:
            self._m_spec_rate.set(
                self._spec_acc_total / self._spec_prop_total)
        if self.paged:
            for i in np.nonzero(self._active)[0]:
                self._rollback_spec_pages(int(i))

    def _apply_multi_emissions(self, toks, events: list,
                               tick_now: float) -> None:
        """Multi-tick (non-spec) post-pull bookkeeping: `toks` is the
        [N, K] emission matrix from multi_tick_scan — column j = the
        token scan step j emitted, MT_PAD after the slot's device-side
        retirement, -1 the quarantine verdict. The host replays the
        columns through `_emit_token` (same exactly-once terminal seam
        as the single-tick loop), amortizing the dispatch gap across
        the K tokens for the ITL histogram; host finish rules fire on
        the same token the device retired on, so mirrors land exactly
        where the device state did for surviving slots."""
        from .multi_tick import MT_PAD
        for i in np.nonzero(self._active)[0]:
            req = self._slot_req[i]
            row = [int(t) for t in np.asarray(toks[i]).reshape(-1)]
            cut = row.index(MT_PAD) if MT_PAD in row else len(row)
            row = row[:cut]
            n_real = sum(1 for t in row if t >= 0)
            share = (tick_now - req._t_last) * 1e3 / max(n_real, 1)
            if not row or row[0] < 0:
                self._on_fault("poisoned", RuntimeError(
                    f"non-finite logits in slot {i} (request {req.id})"))
                self._finish(req, "poisoned")
                continue
            for tok in row:
                if tok < 0:
                    self._on_fault("poisoned", RuntimeError(
                        f"non-finite logits in slot {i} "
                        f"(request {req.id})"))
                    self._finish(req, "poisoned")
                    break
                self._emit_token(i, req, tok, events, tick_now,
                                 itl_ms=share)
                if req.done:
                    break

    # ---------------------------------------------------------- plumbing
    def _free_slot(self) -> Optional[int]:
        for i in range(self.num_slots):
            if self._slot_req[i] is None:
                return i
        return None

    def _admit(self, slot: int, req: Request, events: list) -> None:
        if self.paged:
            return self._admit_paged(slot, req, events)
        t0 = len(req.prompt)
        tb = prompt_bucket(t0, self.max_len, self.bucket_lo)
        padded = np.zeros((1, tb), np.int32)
        padded[0, :t0] = req.prompt
        if req.trace is not None:
            req.trace.end(req._sp_queue)
            req._sp_queue = None
            sp_pf = req.trace.begin("prefill", slot=slot, true_len=t0,
                                    bucket=tb, attempt=req.trace.attempt)
        t_pf0 = time.perf_counter()
        with RecordEvent("serving.prefill"):
            first, self._cache = self._prefill(
                self._params, self._cache, self._rep(padded),
                self._rep(t0, np.int32), self._rep(slot, np.int32),
                self._rep([req.temperature], np.float32),
                self._rep([req.top_k], np.int32),
                self._rep([req.id], np.int32), self._base_key,
                sampling=req.temperature > 0.0)
            # first generated token — the admission's one host pull,
            # under the same watchdog as the tick's
            tok = int(self._pull(first))
        pf_ms = (time.perf_counter() - t_pf0) * 1e3
        if req.trace is not None:
            req.trace.end(sp_pf, final=True)
        if self._tick_log is not None:
            self._tick_log.record_prefill(self._ticks, pf_ms, t0, tb,
                                          True, slot)
        self._m_pre.add()
        if self._quant_info:
            self._m_qmm.add(self._qmm_full)
        if tok < 0:
            # prefill quarantine: the slot was never activated — its
            # (possibly non-finite) cache row is masked stale garbage
            # until the next occupant's prefill overwrites it
            self._on_fault("poisoned", RuntimeError(
                f"non-finite prefill logits (request {req.id})"))
            self._finish(req, "poisoned")
            return
        self._activate_slot(slot, req, tok, events)

    def _activate_slot(self, slot: int, req: Request, tok: int,
                       events: list) -> None:
        """Prefill complete: emit the first token, arm every host
        mirror, and hand the slot to the decode tick (shared by the
        dense admission and the paged final chunk)."""
        now = time.perf_counter()
        self._m_qwait.observe((now - req.t_submit) * 1e3)
        self._slo_ttft.append((now - req.t_submit) * 1e3)
        req._t_last = now
        req.slot = slot
        self._slot_req[slot] = req
        self._positions[slot] = len(req.prompt)
        self._active[slot] = True
        self._cur_tok[slot] = tok
        self._temps[slot] = req.temperature
        self._top_ks[slot] = req.top_k
        self._req_ids[slot] = req.id
        self._gen_idx[slot] = 1
        self._eos_ids[slot] = (-1 if req.eos_id is None
                               else int(req.eos_id))
        self._max_new[slot] = int(req.max_new_tokens)
        self._dirty = True
        if req.trace is not None:
            req._sp_decode = req.trace.begin(
                "decode", slot=slot, attempt=req.trace.attempt)
            req.trace.instant("decode.tick", parent=req._sp_decode,
                              tick=self._ticks, token=tok)
        req.tokens.append(tok)
        events.append((req, tok))
        self._m_tok.add()
        self._maybe_finish(req)

    # ------------------------------------------------- paged scheduling
    def _pages_needed(self, t0: int, max_new: int) -> int:
        """Worst-case page envelope for one request: positions
        0 .. t0 + max_new - 2 get written (the final sampled token
        never is), so ceil((t0 + max_new - 1) / page_size)."""
        return -(-(t0 + max_new - 1) // self.page_size)

    def _plan_admission(self, req: Request):
        """The admission plan: (matched shared page ids, aligned_full,
        suffix_start, need, gross). `need` is the worst-case pages the
        request will still allocate privately (envelope minus
        kept-shared credit); `gross` additionally counts cached pages
        the match pulls back live — they stop being evictable for
        other admissions' reservations the moment we retain them. The
        suffix always re-runs >= 1 prompt token (the first-token
        logits must be computed), so a fully page-aligned match COWs
        its last matched page (aligned_full) and recomputes the last
        prompt token into the private copy.

        The match is a CHAIN of ("dev", page_id) | ("host", key)
        entries: the walk consults the device prefix map first, then
        the host tier (inference/host_kv.py) — a host hit costs one
        page allocation at admission (the swap-in) but zero recomputed
        prompt tokens, so `need` credits only device entries."""
        t0 = len(req.prompt)
        ps = self.page_size
        matched: List[tuple] = []        # ("dev", pid) | ("host", key)
        n_dev = 0
        if self.prefix_sharing:
            for key in self._prefix_keys(req):
                pid = self._pool.lookup(key)
                if pid is not None:
                    matched.append(("dev", pid))
                    n_dev += 1
                elif (self._host_tier is not None
                      and (key in self._host_stage
                           or key in self._host_tier)):
                    matched.append(("host", key))
                else:
                    break
        aligned_full = (bool(matched) and len(matched) == t0 // ps
                        and t0 % ps == 0)
        suffix_start = (t0 - 1) if aligned_full else len(matched) * ps
        need = (self._pages_needed(t0, req.max_new_tokens) - n_dev
                + (1 if aligned_full else 0))
        gross = need + sum(1 for kind, pid in matched
                           if kind == "dev" and self._pool.ref[pid] == 0)
        if gross > self.num_pages - 1:
            # an aligned-full match costs one page over the bare
            # envelope (the COW of its last matched page); in a pool
            # sized exactly to the envelope that can NEVER be
            # satisfied and the request would queue forever — admit
            # unshared instead (submit() guaranteed the envelope fits)
            matched, aligned_full, suffix_start = [], False, 0
            need = gross = self._pages_needed(t0, req.max_new_tokens)
        return matched, aligned_full, suffix_start, need, gross

    def _prefix_keys(self, req: Request):
        """The request's per-page rolled prefix hashes, memoized on the
        Request (the prompt is immutable) — the head-of-line plan runs
        every tick while it waits for pages, and must not re-hash
        O(len(prompt)^2 / page_size) bytes each time."""
        if req._pfx_keys is None:
            ps = self.page_size
            req._pfx_keys = [
                _prefix_key(req.prompt, (j + 1) * ps)
                for j in range(len(req.prompt) // ps)]
        return req._pfx_keys

    def _admit_paged(self, slot: int, req: Request, events: list) -> None:
        """Paged admission: map the shared prompt-prefix pages (bumping
        refcounts), reserve the worst-case remainder, then prefill the
        un-shared suffix — inline when it fits one chunk, otherwise one
        chunk per tick through `_advance_prefill`. The caller
        (`step()`) already checked the reservation fits."""
        matched, aligned_full, suffix_start, need, _ = \
            self._plan_admission(req)
        # capture host-tier page data BEFORE any allocation: alloc()'s
        # device eviction cascades into the host tier's own LRU, which
        # could drop a key this very admission still needs. Prefetched
        # uploads (_prefetch_host) are consumed here; cold hits upload
        # synchronously.
        staged = {}
        for kind, key in matched:
            if kind != "host" or key in staged:
                continue
            pair = self._host_stage.pop(key, None)
            if pair is None and self._host_tier is not None:
                hp = self._host_tier.get(key)
                if hp is not None:
                    pair = (self._rep(hp[0]), self._rep(hp[1]))
            if pair is not None:
                staged[key] = pair
                continue
            # defensive: the tier dropped the key since planning —
            # degrade to an unshared suffix from this page on
            cutoff = matched.index((kind, key))
            matched = matched[:cutoff]
            n_dev = sum(1 for k, _ in matched if k == "dev")
            aligned_full = False
            suffix_start = len(matched) * self.page_size
            need = self._pages_needed(
                len(req.prompt), req.max_new_tokens) - n_dev
            break
        self._pool.reserved += need
        self._slot_reserve[slot] = need
        swapped = False
        for j, (kind, val) in enumerate(matched):
            if kind == "dev":
                self._pool.retain(val)
                self._ptab[slot, j] = val
                continue
            # host swap-in: promote the page back to the device pool,
            # re-register it under its prefix key (future sharers hit
            # device again), and map it shared for this slot
            dk, dv = staged[val]
            pid = self._alloc_slot_page(slot, j)
            self._cache["k"] = self._cache["k"].at[:, pid].set(dk)
            self._cache["v"] = self._cache["v"].at[:, pid].set(dv)
            self._pool.register(pid, val)
            if self._host_tier is not None:
                self._host_tier.swapins += 1
            self._m_swapin.add()
            swapped = True
        if swapped and self._cache_pin:
            # the eager .at[].set writes ran outside the jitted bodies —
            # re-assert the pinned layouts (same seam as _restore_into)
            self._cache = self._pin_cache_host(self._cache)
        if matched:
            self._pt_dirty = True
        req.slot = slot
        self._slot_req[slot] = req
        req.shared_tokens = suffix_start
        req._pf_next = suffix_start
        if req.trace is not None:
            req.trace.end(req._sp_queue, shared_tokens=suffix_start)
            req._sp_queue = None
        if aligned_full:
            # the suffix rewrites the last prompt token's K/V into the
            # last matched page — materialize a private copy first
            self._ensure_private(slot, (len(req.prompt) - 1)
                                 // self.page_size)
        t0 = len(req.prompt)
        if self.prefill_chunk <= 0 or t0 - suffix_start <= \
                self.prefill_chunk:
            self._run_chunk(slot, req, events)
        else:
            self._prefilling.append(slot)

    def _run_chunk(self, slot: int, req: Request, events: list) -> None:
        """One prefill chunk for `slot`: allocate/privatize the pages
        its real tokens land in, run the jitted paged chunk prefill,
        and — on the prompt's FINAL chunk — pull the first token,
        register the full prompt pages for future sharers, and
        activate the slot. Non-final chunks make no host pull."""
        t0 = len(req.prompt)
        ps = self.page_size
        start = req._pf_next
        end = (t0 if self.prefill_chunk <= 0
               else min(start + self.prefill_chunk, t0))
        clen = end - start
        for j in range(start // ps, (end - 1) // ps + 1):
            self._ensure_private(slot, j)
        cb = prompt_bucket(clen, self.max_len, self.bucket_lo)
        padded = np.zeros((1, cb), np.int32)
        padded[0, :clen] = req.prompt[start:end]
        if self._pt_dirty:
            self._cache["pt"] = self._rep(self._ptab)
            self._pt_dirty = False
        final = end == t0
        sp_pf = None
        if req.trace is not None:
            sp_pf = req.trace.begin("prefill", slot=slot,
                                    chunk_start=start, chunk_len=clen,
                                    bucket=cb, final=final,
                                    attempt=req.trace.attempt)
        t_pf0 = time.perf_counter()
        with RecordEvent("serving.prefill"):
            first, self._cache = self._prefill(
                self._params, self._cache, self._rep(padded),
                self._rep(clen, np.int32),
                self._rep(start, np.int32),
                self._rep(slot, np.int32),
                self._rep([req.temperature], np.float32),
                self._rep([req.top_k], np.int32),
                self._rep([req.id], np.int32), self._base_key,
                sampling=final and req.temperature > 0.0)
            tok = int(self._pull(first)) if final else None
        pf_ms = (time.perf_counter() - t_pf0) * 1e3
        if req.trace is not None:
            req.trace.end(sp_pf)
        if self._tick_log is not None:
            self._tick_log.record_prefill(self._ticks, pf_ms, clen, cb,
                                          final, slot)
        self._m_chunks.add()
        if self._quant_info:
            self._m_qmm.add(self._qmm_full)
        if not final:
            req._pf_next = end
            return
        req._pf_next = None
        self._m_pre.add()
        if tok < 0:
            # prefill quarantine BEFORE registration: a poisoned
            # prompt's pages are never published to the prefix map
            self._on_fault("poisoned", RuntimeError(
                f"non-finite prefill logits (request {req.id})"))
            self._finish(req, "poisoned")
            return
        if self.prefix_sharing:
            for j, key in enumerate(self._prefix_keys(req)):
                self._pool.register(int(self._ptab[slot, j]), key)
        self._activate_slot(slot, req, tok, events)

    def _advance_prefill(self, events: list, actions: dict) -> None:
        """The chunked-prefill interleave: at most ONE chunk runs per
        tick (FCFS across mid-prefill slots), so co-batched decode
        streams pay at most one chunk of latency per token no matter
        how long a joining prompt is."""
        while self._prefilling:
            slot = self._prefilling[0]
            req = self._slot_req[slot]
            if req is None or req.done or req._pf_next is None:
                self._prefilling.popleft()     # evicted/cancelled
                continue
            self._chunk_guarded(slot, req, events, actions)
            if req.done or req._pf_next is None:
                if self._prefilling and self._prefilling[0] == slot:
                    self._prefilling.popleft()
            return

    def _chunk_guarded(self, slot: int, req: Request, events: list,
                       actions: dict) -> None:
        """One chunk under the fault guard. A chunk re-run is
        idempotent (the same pages re-scatter the same K/V), so a
        raising device call just retries with backoff; an exhausted
        budget evicts the request (its pages free via _clear_slot) and
        a hung pull / dead donated cache hard-resets."""
        from ..parallel.resilience import StepHungError
        for attempt in range(self.retries + 1):
            try:
                if actions.pop("raise_prefill", None):
                    raise ServingFaultError("injected prefill fault")
                self._run_chunk(slot, req, events)
                return
            except StepHungError as e:
                self._on_fault("prefill_hang", e)
                self._finish(req, "evicted")
                self._hard_reset("prefill watchdog hang")
                return
            except Exception as e:                 # noqa: BLE001
                self._on_fault("prefill", e)
                dead = self._cache_dead()
                if dead or attempt >= self.retries:
                    self._finish(req, "evicted")
                    if dead:
                        self._hard_reset("prefill lost the donated cache")
                    return
                self._backoff(attempt)

    def _alloc_slot_page(self, slot: int, j: int) -> int:
        """Allocate a private page for table entry (slot, j),
        consuming the slot's admission reservation when one remains."""
        pid = self._pool.alloc()
        if self._slot_reserve[slot] > 0:
            self._slot_reserve[slot] -= 1
            self._pool.reserved -= 1
        self._ptab[slot, j] = pid
        self._pt_dirty = True
        return pid

    def _ensure_private(self, slot: int, j: int) -> int:
        """THE copy-on-write seam: make table entry (slot, j) safe to
        write. Unmapped -> allocate; mapped but frozen (shared refcount
        or prefix-registered) -> allocate a fresh page, jitted-copy the
        frozen page's contents into it, swap the table entry, and drop
        the reference; already private -> no-op."""
        pid = int(self._ptab[slot, j])
        if pid != 0 and not self._pool.is_frozen(pid):
            return pid
        if pid != 0 and self._raise_cow:
            self._raise_cow = False
            raise ServingFaultError("injected cow fault")
        new = self._alloc_slot_page(slot, j)
        if pid != 0:
            self._cache = self._cow(self._cache,
                                    self._rep(pid, np.int32),
                                    self._rep(new, np.int32))
            self._pool.release(pid)
            self._m_cow.add()
        return new

    def _prepare_tick_pages(self) -> None:
        """Paged pre-tick: every active slot's write page (where its
        position lands this tick) must exist and be private before the
        jitted scatter runs. Allocation draws on the slot's admission
        reservation, so it cannot fail mid-decode. Under speculative
        decode the tick writes gamma+1 positions, so the whole span's
        pages prepare — CLAMPED to the request's write envelope
        (position t0 + max_new - 2 is the last ever written; draft
        positions past it scatter to the scratch page through the
        unmapped table instead of drawing pages the admission never
        reserved)."""
        span = self._tick_span     # K ticks x (gamma+1 under spec)
        for i in np.nonzero(self._active)[0]:
            pos = int(self._positions[i])
            last = pos + span - 1
            req = self._slot_req[int(i)]
            if req is not None:
                last = min(last,
                           len(req.prompt) + req.max_new_tokens - 2)
            # positions pos..last are contiguous -> iterate the pages
            # they cover once each (<= ceil(span/ps)+1), not once per
            # position: _ensure_private is a host table read + set
            # lookup on the scheduler hot path
            for j in range(pos // self.page_size,
                           last // self.page_size + 1):
                if j < self.max_pages:
                    self._ensure_private(int(i), j)

    def _rollback_spec_pages(self, slot: int) -> None:
        """Undo speculative page allocation: after acceptance, any
        page mapped past the slot's live position holds ONLY rejected
        drafts' K/V — release it to the pool and restore the slot's
        admission reservation, so between ticks the pool accounting is
        byte-identical to the single-token path's (speculation can
        never starve other admissions of pages). Decode-range pages
        are always private and unregistered (registration happens at
        prefill, for prompt pages, which all sit below the live
        position), so release() returns them straight to the free
        list."""
        pos = int(self._positions[slot])
        ps = self.page_size
        row = self._ptab[slot]
        first = -(-pos // ps)        # page j holds a token iff j*ps < pos
        # only THIS tick's prepared span can be mapped past `first`
        # (rollback restores the invariant every tick, and positions
        # only grow): its last write position is pos_before + gamma
        # <= pos - 1 + gamma, so the scan is O(gamma/page_size), not
        # O(max_pages), per slot per tick
        last = min((pos + self._tick_span - 2) // ps + 1, self.max_pages)
        for j in range(first, last):
            pid = int(row[j])
            if pid == 0:
                continue
            self._pool.release(pid)
            self._slot_reserve[slot] += 1
            self._pool.reserved += 1
            row[j] = 0
            self._pt_dirty = True

    # ---------------------------------------- live migration + rebuild
    def _pin_cache_host(self, cache):
        """Re-assert the pinned layouts after an EAGER cache update
        (the migration restore writes run outside the jitted bodies).
        A jitted identity with the SAME out_shardings `_new_cache`
        allocates under — not a bare device_put — because jit
        NORMALIZES PartitionSpec spellings (trailing Nones stripped):
        a device_put'd leaf would carry an equivalent-but-differently-
        spelled sharding, and the next decode tick would silently
        compile a second executable for it. No-op off-mesh."""
        if not self._cache_pin:
            return cache
        if self._repin is None:
            # Strip trailing Nones from the pin specs: jit OUTPUTS carry
            # the trimmed spelling, and equivalent-but-longer spellings
            # are DIFFERENT pjit cache keys — without this the first
            # post-restore tick compiles against a spelling no later
            # tick ever reproduces (a permanent extra executable).
            norm = {}
            for k, s in self._cache_pin.items():
                if s is None:
                    norm[k] = None
                    continue
                parts = list(s.spec)
                while parts and parts[-1] is None:
                    parts.pop()
                norm[k] = jax.sharding.NamedSharding(
                    s.mesh, jax.sharding.PartitionSpec(*parts))
            self._repin = jax.jit(lambda c: c, out_shardings=norm)
        return self._repin(cache)

    def snapshot_request(self, req: Request) -> Optional[dict]:
        """Host-snapshot a mid-decode request's LIVE state for cross-
        engine migration: the already-computed K/V of every written
        position (dense: the slot row's prefix; paged: the mapped
        pages, flattened to one contiguous [L, pos, KV, hd] block —
        layout-neutral, so a dense engine can restore a paged
        snapshot and vice versa) plus the decode-state mirror (pos /
        cur_tok / gen_idx and the PRNG id, so sampled streams continue
        bit-identically). Returns None when there is nothing to
        migrate — the request is terminal, still queued, or mid-
        chunked-prefill (no first token yet; a replay costs the same
        prefill it would need anyway). Call BETWEEN ticks only (the
        scheduler's context — the same contract as submit/cancel).
        Raises ServingFaultError under the injected migrate_raise
        fault so drills exercise the fallback-to-replay path."""
        slot = req.slot
        if (req.done or slot is None or req._pf_next is not None
                or not self._active[slot]):
            return None
        if self._raise_migrate:
            self._raise_migrate = False
            raise ServingFaultError("injected migrate fault")
        pos = int(self._positions[slot])
        if self.paged:
            ps = self.page_size
            npg = -(-pos // ps)
            pids = np.asarray(self._ptab[slot, :npg], np.int32)
            # gather the mapped pages -> [L, npg, ps, KV, hd], flatten
            # the (page, in-page) axes (already position-ordered), and
            # truncate to the written prefix
            k = np.asarray(self._cache["k"][:, pids])
            v = np.asarray(self._cache["v"][:, pids])
            k = k.reshape(k.shape[0], npg * ps, *k.shape[3:])[:, :pos]
            v = v.reshape(v.shape[0], npg * ps, *v.shape[3:])[:, :pos]
        else:
            k = np.asarray(self._cache["k"][:, slot, :pos])
            v = np.asarray(self._cache["v"][:, slot, :pos])
        return {"prompt": np.asarray(req.prompt, np.int32),
                "tokens": list(req.tokens),
                "max_new_tokens": int(req.max_new_tokens),
                "temperature": float(req.temperature),
                "top_k": int(req.top_k),
                "eos_id": req.eos_id,
                "tenant": req.tenant,
                "priority": req.priority,
                "pos": pos,
                "cur_tok": int(self._cur_tok[slot]),
                "gen_idx": int(self._gen_idx[slot]),
                "prng_id": int(self._req_ids[slot]),
                "kv_k": k, "kv_v": v,
                "kv_bytes": int(k.nbytes + v.nbytes)}

    def restore_request(self, snap: dict,
                        deadline_s: Optional[float] = None,
                        deadline_ticks: Optional[int] = None,
                        _trace=None) -> Optional[Request]:
        """Admit a migrated snapshot into THIS engine, bypassing the
        queue (the request is already mid-flight — queueing would
        re-order it behind cold admissions): a free slot is claimed
        directly, the paged restore reserves the request's REMAINING
        worst-case page envelope through the same admission-
        reservation accounting as submit (pages already holding the
        snapshot allocate now; the rest reserve), and the K/V block
        uploads with ZERO re-prefilled tokens. Deadlines are the
        REMAINING budget (the caller re-scopes — see
        EngineRouter._remaining_budget). Returns the new live Request
        (its .tokens pre-seeded with the already-generated ids so
        eos/length checks continue where the source left off), or None
        when this engine cannot take it (no free slot / pages / shape
        limits) — the caller falls back to requeue-replay."""
        prompt = np.asarray(snap["prompt"], np.int32).reshape(-1)
        t0 = prompt.shape[0]
        max_new = int(snap["max_new_tokens"])
        if t0 + max_new > self.max_len:
            return None
        if snap["top_k"] > self.max_top_k:
            return None
        slot = self._free_slot()
        if slot is None:
            return None
        pos = int(snap["pos"])
        if self.paged:
            need = self._pages_needed(t0, max_new)
            if need > self._pool.available():
                return None
        req = Request(self._next_id, prompt, max_new,
                      float(snap["temperature"]), int(snap["top_k"]),
                      snap["eos_id"],
                      deadline_s=(None if deadline_s is None
                                  else float(deadline_s)),
                      deadline_ticks=(None if deadline_ticks is None
                                      else int(deadline_ticks)),
                      tenant=str(snap.get("tenant", "default")),
                      priority=int(snap.get("priority", 0)))
        self._next_id += 1
        req.t_submit = time.perf_counter()
        req._tick_submit = self._ticks
        req._engine = self
        req.tokens = list(snap["tokens"])
        req.trace = _trace
        self._restore_into(req, snap, slot)
        self._m_sub.add()
        return req

    def _restore_into(self, req: Request, snap: dict, slot: int) -> None:
        """Write a snapshot's K/V into `slot` and arm every host
        mirror — the shared tail of cross-engine restore and the
        in-place mesh rebuild. The writes are EAGER in-pool updates
        (migration is rare; the jitted tick bodies and their trace
        caches are untouched), re-pinned to the mesh layout so the
        next donated tick aliases exactly. The PRNG id mirror carries
        the SOURCE engine's id — `_slot_keys` folds the mirror, not
        the Request, into the stream, so sampled continuations are
        bit-identical to the undisturbed engine."""
        pos = int(snap["pos"])
        kv_k, kv_v = snap["kv_k"], snap["kv_v"]
        if self.paged:
            ps = self.page_size
            npg = -(-pos // ps)
            need = self._pages_needed(len(req.prompt),
                                      req.max_new_tokens)
            L = kv_k.shape[0]
            pad = np.zeros((L, npg * ps) + kv_k.shape[2:], kv_k.dtype)
            padv = np.zeros_like(pad)
            pad[:, :pos] = kv_k
            padv[:, :pos] = kv_v
            for j in range(npg):
                pid = self._pool.alloc()
                self._ptab[slot, j] = pid
                self._cache["k"] = self._cache["k"].at[:, pid].set(
                    self._rep(pad[:, j * ps:(j + 1) * ps]))
                self._cache["v"] = self._cache["v"].at[:, pid].set(
                    self._rep(padv[:, j * ps:(j + 1) * ps]))
            reserve = max(need - npg, 0)
            self._slot_reserve[slot] = reserve
            self._pool.reserved += reserve
            self._pt_dirty = True
        else:
            self._cache["k"] = self._cache["k"].at[
                :, slot, :pos].set(self._rep(kv_k))
            self._cache["v"] = self._cache["v"].at[
                :, slot, :pos].set(self._rep(kv_v))
        self._cache = self._pin_cache_host(self._cache)
        now = time.perf_counter()
        req.slot = slot
        req._t_last = now
        self._slot_req[slot] = req
        self._positions[slot] = pos
        self._active[slot] = True
        self._cur_tok[slot] = int(snap["cur_tok"])
        self._temps[slot] = req.temperature
        self._top_ks[slot] = req.top_k
        self._req_ids[slot] = int(snap["prng_id"])
        self._gen_idx[slot] = int(snap["gen_idx"])
        self._eos_ids[slot] = (-1 if req.eos_id is None
                               else int(req.eos_id))
        self._max_new[slot] = int(req.max_new_tokens)
        self._dirty = True
        if req.trace is not None:
            req._sp_decode = req.trace.begin(
                "decode", slot=slot, migrated=True,
                attempt=req.trace.attempt)

    def detach_request(self, req: Request) -> bool:
        """Non-terminal release — the live-migration seam. Drops `req`
        from THIS engine (slot, pages, reservation, queue) WITHOUT the
        terminal transition: the request continues on another engine,
        so its trace stays OPEN (only the open decode span closes) and
        no terminal-reason counter fires. finish_reason is the
        sentinel "migrated" — deliberately NOT in TERMINAL_REASONS,
        because for this engine the request did not terminate, it
        left. requests_completed still advances so submitted-completed
        stays a true in-flight gauge. Returns False when the request
        already resolved."""
        if req.done:
            return False
        if req.slot is not None:
            self._clear_slot(req.slot)
        else:
            try:
                self._queue.remove(req)
            except ValueError:
                pass
        req.slot = None
        req.done = True
        req.finish_reason = "migrated"
        if req.trace is not None and req._sp_decode is not None:
            req.trace.end(req._sp_decode)
            req._sp_decode = None
        self._m_done.add()
        self._m_occ.set(int(self._active.sum()))
        self._m_queue.set(len(self._queue))
        return True

    def rebuild_on_mesh(self, mesh) -> int:
        """Preemption recovery: re-host THIS engine on a (typically
        smaller) mesh without dropping its live streams. Every active
        slot host-snapshots (`snapshot_request`), params re-host
        through device_get -> `_shard_params` onto the new mesh (the
        simulated-loss drill's seam — a production loss would re-read
        weights from their source), the pool cache reallocates via
        `_new_cache` under a FRESH `_cache_pin` (sharded-birth
        discipline: no device ever stages the whole pool), the jitted
        bodies re-make (`_make_executables` — one warmup recompile
        each, then the trace ceilings hold), and the snapshots restore
        IN PLACE onto the SAME Request objects — callers' handles keep
        filling, zero re-prefilled tokens, streams bit-identical.
        Requests that cannot snapshot (mid-chunked-prefill) resolve
        "evicted"; queued requests stay queued and prefill on the new
        mesh. Returns the number of live streams migrated."""
        if self.tp_axis not in mesh.axis_names:
            raise ValueError(
                f"mesh {dict(mesh.shape)} has no {self.tp_axis!r} axis")
        if self.family.serving_specs is None:
            raise ValueError(
                f"family {self.family.name!r} has no "
                "SERVING_PARAM_SPECS — it cannot run tensor-parallel")
        snaps = []
        for req in list(self._slot_req):
            if req is None:
                continue
            try:
                snap = self.snapshot_request(req)
            except Exception as e:             # noqa: BLE001
                self._on_fault("migrate", e)
                snap = None
            if snap is None:
                self._finish(req, "evicted")
            else:
                slot = req.slot
                self._clear_slot(slot)         # old pool's accounting
                req.slot = None
                snaps.append((req, snap))
        # host copies BEFORE the old mesh state is dropped
        params_host = jax.device_get(self._params)
        key_host = np.asarray(jax.device_get(self._base_key))
        from jax.sharding import NamedSharding, PartitionSpec
        self.mesh = mesh
        self.tp = int(mesh.shape[self.tp_axis])
        self._rep_sharding = NamedSharding(mesh, PartitionSpec())
        self._cache_pin = None
        self._params = self._shard_params(params_host)
        if self.paged:
            self._pool = _PagePool(self.num_pages, self.page_size)
            self._ptab[:] = 0
            self._slot_reserve[:] = 0
            self._prefilling.clear()
            self._pt_dirty = False
        self._cache = self._new_cache()        # re-pins the layout
        self._base_key = self._rep(key_host)
        self._poison_ones = self._rep(np.ones(self.num_slots,
                                              np.float32))
        self._dstate = None
        self._dirty = True
        self._make_executables()
        for req, snap in snaps:
            slot = self._free_slot()
            self._restore_into(req, snap, slot)
        self._flight.note(serving_rebuild=dict(mesh.shape),
                          tick=self._ticks, migrated=len(snaps))
        self._flight.dump("serving_rebuild")
        print(f"[serving] rebuilt on mesh {dict(mesh.shape)} at tick "
              f"{self._ticks}: {len(snaps)} live stream(s) migrated",
              file=sys.stderr, flush=True)
        return len(snaps)

    def _maybe_finish(self, req: Request) -> None:
        slot = req.slot
        if req.eos_id is not None and req.tokens[-1] == req.eos_id:
            self._finish(req, "eos")
        elif len(req.tokens) >= req.max_new_tokens:
            self._finish(req, "length")
        elif slot is not None and self._positions[slot] >= self.max_len:
            self._finish(req, "evicted")  # cache full — unreachable via
            #                               submit's length check

    # --------------------------------------------------------- SLO stats
    def slo_snapshot(self) -> dict:
        """The raw SLO samples (ms): time-to-first-token (queue wait
        included) and inter-token latency, bounded rings."""
        return {"ttft_ms": [round(v, 3) for v in self._slo_ttft],
                "itl_ms": [round(v, 3) for v in self._slo_itl]}

    def export_slo_jsonl(self, path: str) -> None:
        """Append one serving_slo record to a telemetry JSONL file and
        DRAIN the sample rings: each record covers the window since the
        previous export, so a periodic exporter (the natural cadence,
        alongside monitor.export_jsonl) never double-counts —
        tools/telemetry_report.py merges all records' samples into the
        serving section's TTFT / inter-token p50/p95/p99."""
        rec = {"kind": "serving_slo", "t": time.time(),
               **self.slo_snapshot()}
        self._slo_ttft.clear()
        self._slo_itl.clear()
        with open(path, "a") as f:
            f.write(json.dumps(rec) + "\n")


def create_serving_engine(model_or_params, cfg=None, **kw) -> ServingEngine:
    """Build a ServingEngine from a facade model (GPTModel/LlamaModel —
    family and params are inferred) or from a raw (params, cfg) pair
    plus family=..."""
    from ..models.facade import FacadeModel
    if isinstance(model_or_params, FacadeModel):
        model = model_or_params
        family = kw.pop("family", getattr(model, "_serving_family", None))
        if family is None:
            raise ValueError(f"{type(model).__name__} does not name a "
                             "_serving_family; pass family=...")
        from ..framework.dispatch import raw_value
        params = {n: raw_value(p) for n, p in model._params.items()}
        return ServingEngine(params, model.cfg, family=family, **kw)
    if cfg is None:
        raise ValueError("create_serving_engine(params, cfg, ...) needs "
                         "the model config")
    return ServingEngine(model_or_params, cfg, **kw)
