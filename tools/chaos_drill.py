"""Chaos drill: run training under injected faults, assert recovery.

The executable acceptance test for the fault-tolerance runtime
(docs/fault_tolerance.md). The reference stack has nothing like it
(SURVEY.md: "no systematic fault-injection harness") — here every
scenario spawns the REAL elastic-lite launcher on the 8-virtual-device
CPU mesh, injects a declared fault (paddle_tpu.testing.faults), and
asserts the restarted/resumed run's loss trajectory matches an
uninterrupted baseline step for step.

Scenarios:
  kill@S          worker hard-killed before step S; restart resumes LATEST
  crash_shard@S:K worker dies mid-save_sharded; torn staging dir ignored
  nan@S:2         two poisoned steps -> skip, skip, rollback, clean re-run
  elastic_exit@S  worker exits 101; launcher's elastic budget restarts it
  hb_stale@S      heartbeat wedge; launcher hang watchdog kills + restarts
  corrupt         newest snapshot truncated/bit-flipped between two legs;
                  resume must fall back to the previous intact snapshot

Elastic scenario group (--elastic; ISSUE 14): an 8-virtual-device
dp2×fsdp2×tp2 GPT train run loses a device at every phase — mid-step,
mid-async-save (a background writer in flight at the loss boundary),
mid-restore (a second loss DURING the replan's reshard-restore) — plus
a collective hang, a within-budget straggler (must NOT replan), and an
exit-101 restart that carries a DEGRADED world spec through the
launcher. Each scenario asserts: resumed on a degraded plan, the
post-restore loss trajectory BIT-identical to a clean restore of the
same checkpoint on the same degraded plan (the worker replays it
in-process), zero recompiles after the replan warmup (trace_count), a
parseable flight dump AND telemetry JSONL with the train.elastic.*
counters moved.

Usage:
  python tools/chaos_drill.py --quick          # representative phases
  python tools/chaos_drill.py --full           # kill/crash at EVERY step
  python tools/chaos_drill.py --elastic        # device-loss scenarios
  python tools/chaos_drill.py --serving        # serving chaos drill
                                               # (chaos_serving --quick)
  python tools/chaos_drill.py --bench          # save/verify overhead JSON
  python tools/chaos_drill.py --gate [T1LOG]   # pre-commit robustness
                                               # gate: quick+elastic+
                                               # serving drills green
                                               # AND diff_failures clean
(The launcher re-enters this file with --worker; not for direct use.)
"""
from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

STEPS_ENV = "PADDLE_TPU_DRILL_STEPS"
CKPT_ENV = "PADDLE_TPU_DRILL_CKPT"
OUT_ENV = "PADDLE_TPU_DRILL_OUT"
TELE_ENV = "PADDLE_TPU_DRILL_TELEMETRY"
MODE_ENV = "PADDLE_TPU_DRILL_MODE"           # "" | "elastic"
ASYNC_ENV = "PADDLE_TPU_DRILL_ASYNC"         # "1" -> async checkpoints
EXIT101_ENV = "PADDLE_TPU_DRILL_EXIT101"     # "1" -> restart_on_loss
STEP_TO_ENV = "PADDLE_TPU_DRILL_STEP_TIMEOUT"  # watchdog budget (s)
SUMMARY_ENV = "PADDLE_TPU_DRILL_SUMMARY"     # elastic summary JSON path

DIM_IN, DIM_H = 16, 32
BATCH = 8


# =========================================================== worker side
def _batch(step: int):
    import numpy as np
    rng = np.random.RandomState(10_000 + step)
    x = rng.randn(BATCH, DIM_IN).astype(np.float32)
    y = rng.randn(BATCH).astype(np.float32)
    return x, y


def worker_main() -> int:
    from paddle_tpu.testing import faults
    faults.install()

    import numpy as np
    import jax
    import jax.numpy as jnp
    from paddle_tpu.parallel.mesh import build_mesh, use_mesh, \
        shard_value, P
    from paddle_tpu.parallel.checkpoint import CheckpointManager
    from paddle_tpu.parallel.resilience import (ResilientTrainer,
                                                ResilienceConfig,
                                                run_resilient)

    steps = int(os.environ[STEPS_ENV])
    mgr = CheckpointManager(os.environ[CKPT_ENV], max_to_keep=3)
    out = open(os.environ[OUT_ENV], "a")
    telemetry = None
    if os.environ.get(TELE_ENV):
        from paddle_tpu.profiler.telemetry import TelemetryPipeline
        from paddle_tpu.parallel.resilience import RESILIENT_FIELDS
        telemetry = TelemetryPipeline(os.environ[TELE_ENV], every=4,
                                      fields=RESILIENT_FIELDS,
                                      meta={"samples_per_step": BATCH})

    def init_params(key):
        k1, k2 = jax.random.split(key)
        return {"w1": jax.random.normal(k1, (DIM_IN, DIM_H)) * 0.3,
                "w2": jax.random.normal(k2, (DIM_H,)) * 0.3}

    def train_step(params, opt_state, batch, lr=0.05, mu=0.9):
        x, y = batch

        def loss_fn(p):
            h = jnp.maximum(x @ p["w1"], 0.0)
            return jnp.mean((h @ p["w2"] - y) ** 2)

        loss, grads = jax.value_and_grad(loss_fn)(params)
        new_opt = jax.tree_util.tree_map(
            lambda m, g: mu * m + g, opt_state, grads)
        new_params = jax.tree_util.tree_map(
            lambda p, m: p - lr * m, params, new_opt)
        return loss, new_params, new_opt

    mesh = build_mesh({"dp": 2, "mp": 4})
    specs = {"w1": P(None, "mp"), "w2": P("mp")}
    with use_mesh(mesh):
        params = {k: shard_value(v, specs[k], mesh)
                  for k, v in init_params(jax.random.PRNGKey(0)).items()}
        opt_state = jax.tree_util.tree_map(jnp.zeros_like, params)
        tr = ResilientTrainer(
            train_step, params, opt_state, manager=mgr,
            config=ResilienceConfig(checkpoint_every=1, rollback_after=2,
                                    max_rollbacks=5),
            telemetry=telemetry)
        if tr.maybe_resume():
            print(f"[drill-worker] resumed at step {tr.step}",
                  file=sys.stderr, flush=True)

        def record(step, loss, ok):
            out.write(json.dumps(
                {"step": step, "loss": loss, "ok": ok}) + "\n")
            out.flush()
            os.fsync(out.fileno())

        def sharded_batch(step):
            x, y = _batch(step)
            return (shard_value(jnp.asarray(x), P("dp", None), mesh),
                    shard_value(jnp.asarray(y), P("dp"), mesh))

        run_resilient(tr, sharded_batch, steps, on_step=record)
    if telemetry is not None:
        telemetry.close(tr._tstate)
    print(f"[drill-worker] done: {tr.step} steps, {tr.skipped} skipped, "
          f"{tr.rollbacks} rollbacks", file=sys.stderr, flush=True)
    return 0


# ==================================================== elastic worker side
def elastic_worker_main() -> int:
    """The ISSUE-14 elastic drill worker: a tiny dp2×fsdp2×tp2 GPT
    train run under the ElasticTrainer. After the run it REPLAYS the
    post-replan trajectory from the restored checkpoint on the same
    degraded plan (a fresh step, a clean restore) and writes a summary
    JSON the driver asserts bit-identity/trace-count/world from."""
    from paddle_tpu.testing import faults
    faults.install()

    import numpy as np
    import jax
    import jax.numpy as jnp
    from paddle_tpu.models.facade import make_train_step
    from paddle_tpu.models.gpt import (GPTConfig, init_gpt_params,
                                       init_opt_state, train_step)
    from paddle_tpu.parallel.checkpoint import (CheckpointManager,
                                                load_sharded)
    from paddle_tpu.parallel.elastic import (ElasticConfig,
                                             ElasticTrainer,
                                             run_elastic)
    from paddle_tpu.parallel.planner import plan_train
    from paddle_tpu.parallel.resilience import (RESILIENT_FIELDS,
                                                ResilienceConfig)
    from paddle_tpu.distributed.launch.heartbeat import degraded_world

    steps = int(os.environ[STEPS_ENV])
    mgr = CheckpointManager(os.environ[CKPT_ENV], max_to_keep=0)
    out = open(os.environ[OUT_ENV], "a")
    telemetry = None
    if os.environ.get(TELE_ENV):
        from paddle_tpu.profiler.telemetry import TelemetryPipeline
        telemetry = TelemetryPipeline(os.environ[TELE_ENV], every=2,
                                      fields=RESILIENT_FIELDS,
                                      meta={"samples_per_step": BATCH})

    B, S = 8, 8
    cfg = GPTConfig(vocab_size=128, hidden_size=32, num_layers=1,
                    num_heads=2, max_seq_len=16, dtype=jnp.float32,
                    remat=False, sequence_parallel=False)

    def batch(step):
        return np.random.RandomState(4242 + step).randint(
            0, 128, (B, S + 1)).astype(np.int32)

    # a restarted worker granted a degraded world plans onto it
    # EXPLICITLY (the spec's axes), so the resumed plan is the one the
    # dying worker degraded to — not whatever the search would pick
    granted = degraded_world()
    if granted and granted.get("axes"):
        ax = granted["axes"]
        plan = plan_train(cfg, int(granted["n_devices"]), B,
                          dp=ax.get("dp", 1), fsdp=ax.get("fsdp", 1),
                          tp=ax.get("tp", 1))
        print(f"[elastic-worker] degraded world granted: {granted}",
              file=sys.stderr, flush=True)
    else:
        plan = plan_train(cfg, 8, B, dp=2, fsdp=2, tp=2)
    params = init_gpt_params(cfg, jax.random.PRNGKey(0))
    opt = init_opt_state(params)
    ecfg = ElasticConfig(
        heartbeat_timeout=60.0,
        step_timeout=float(os.environ.get(STEP_TO_ENV, "0") or 0),
        hang_retries=0,
        restart_on_loss=os.environ.get(EXIT101_ENV) == "1")
    rcfg = ResilienceConfig(
        checkpoint_every=1,
        async_checkpoint=os.environ.get(ASYNC_ENV) == "1")
    et = ElasticTrainer(train_step, params, opt, cfg=cfg,
                        global_batch=B, manager=mgr, plan=plan,
                        config=ecfg, resilience=rcfg,
                        telemetry=telemetry, lr=1e-3)
    resumed_at = None
    if et.maybe_resume():
        resumed_at = et.step
        print(f"[elastic-worker] resumed at step {et.step}",
              file=sys.stderr, flush=True)

    losses = {}

    def record(step, loss, ok):
        losses[step] = loss
        out.write(json.dumps(
            {"step": step, "loss": loss, "ok": ok}) + "\n")
        out.flush()
        os.fsync(out.fileno())

    run_elastic(et, batch, steps, on_step=record)
    mgr.wait()                       # flush any in-flight async save
    if telemetry is not None:
        telemetry.close(et._trainer._tstate)

    # ---- post-run self-check: clean restore on the degraded plan ----
    # in-process replan records last_restore_step; an exit-101 restart
    # resumed at `resumed_at` on the granted world — same anchor
    anchor = et.last_restore_step if et.last_restore_step is not None \
        else resumed_at
    summary = {
        "replans": et.replans,
        "world": len(et.world),
        "axes": et.plan.axes,
        "trace_count": et.trace_count,
        "restored_step": anchor,
        "degraded": len(et.world) < 8 or bool(granted),
        "steps_recorded": sorted(losses),
    }
    if anchor is not None:
        from paddle_tpu.parallel.resilience import plan_state_specs
        mesh_d = et.plan.build_mesh(devices=et.world)
        specs = plan_state_specs(et.plan)
        state = load_sharded(
            os.path.join(os.environ[CKPT_ENV], f"ckpt-{anchor}"),
            mesh=mesh_d, specs=specs)
        step2 = make_train_step(train_step, cfg=cfg, lr=1e-3,
                                mesh=mesh_d, plan=et.plan)
        p2, o2 = state["params"], state["opt_state"]
        mism = []
        for s in range(int(anchor), steps):
            loss, p2, o2 = step2(p2, o2, batch(s))
            if float(loss) != losses.get(s):
                mism.append((s, float(loss), losses.get(s)))
        summary["replay_identical"] = not mism
        summary["replay_mismatches"] = mism[:5]
    with open(os.environ[SUMMARY_ENV], "w") as f:
        json.dump(summary, f)
    print(f"[elastic-worker] done: {et.step} steps, "
          f"{et.replans} replans, world {len(et.world)}, "
          f"axes {et.plan.axes}", file=sys.stderr, flush=True)
    return 0


# =========================================================== driver side
def _check_flight(scenario_dir: str, min_steps: int = 1):
    """A killed/restarted worker must leave at least one parseable
    flight-recorder dump carrying step records and a monitor snapshot
    (the PR-3 acceptance criterion). Returns an error string or None."""
    fdir = os.path.join(scenario_dir, "flight")
    dumps = sorted(f for f in (os.listdir(fdir) if os.path.isdir(fdir)
                               else []) if f.endswith(".json"))
    if not dumps:
        return f"no flight-recorder dump under {fdir}"
    for name in dumps:
        try:
            with open(os.path.join(fdir, name)) as f:
                doc = json.load(f)
        except (OSError, ValueError) as e:
            return f"flight dump {name} unparseable: {e}"
        if doc.get("kind") != "flight_recorder":
            return f"flight dump {name}: wrong kind {doc.get('kind')!r}"
        if "monitor" not in doc:
            return f"flight dump {name}: no monitor snapshot"
    best = 0
    for name in dumps:
        with open(os.path.join(fdir, name)) as f:
            best = max(best, len(json.load(f).get("steps") or []))
    if best < min_steps:
        return (f"flight dumps under {fdir} carry {best} step records "
                f"(< {min_steps})")
    return None


def _check_telemetry(scenario_dir: str):
    """The scenario's telemetry JSONL must summarize cleanly and carry
    step records (torn tails from kills are tolerated by the parser)."""
    path = os.path.join(scenario_dir, "telemetry.jsonl")
    if not os.path.exists(path):
        return f"no telemetry JSONL at {path}"
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from telemetry_report import summarize
    try:
        doc = summarize(path)
    except Exception as e:
        return f"telemetry summary failed for {path}: {e}"
    if doc.get("steps_recorded", 0) < 1:
        return f"telemetry JSONL {path} has no step records"
    return None


def _trajectory(out_path: str):
    """results.jsonl -> {step: last recorded loss} (re-runs after a
    restart/rollback overwrite earlier occurrences)."""
    traj = {}
    if not os.path.exists(out_path):
        return traj
    with open(out_path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            rec = json.loads(line)
            traj[rec["step"]] = rec["loss"]
    return traj


def _launch(scenario_dir: str, steps: int, fault_spec: str,
            hang_watch: bool, max_restart: int = 10,
            timeout: int = 600, extra_env=None):
    ckpt = os.path.join(scenario_dir, "ckpt")
    outp = os.path.join(scenario_dir, "out.jsonl")
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)   # workers pin CPU via the boot shim
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env[STEPS_ENV] = str(steps)
    env[CKPT_ENV] = ckpt
    env[OUT_ENV] = outp
    env[SUMMARY_ENV] = os.path.join(scenario_dir, "summary.json")
    if extra_env:
        env.update(extra_env)
    # observability riders: every worker leaves a crash flight recorder
    # black box + a batched-telemetry JSONL the driver parses back
    env["PADDLE_TPU_FLIGHT_DIR"] = os.path.join(scenario_dir, "flight")
    env[TELE_ENV] = os.path.join(scenario_dir, "telemetry.jsonl")
    if fault_spec:
        env["PADDLE_TPU_FAULTS"] = fault_spec
        env["PADDLE_TPU_FAULTS_ONCE_DIR"] = os.path.join(
            scenario_dir, "once")
    cmd = [sys.executable, "-m", "paddle_tpu.distributed.launch",
           "--devices", "cpu", "--cpus_per_proc", "8",
           "--max_restart", str(max_restart),
           "--max_elastic_restart", "8"]
    if hang_watch:
        # generous: worker boot (paddle_tpu + jax import) takes >5s on a
        # loaded 1-core host and a false hang burns the restart budget
        cmd += ["--hang_timeout", "15", "--heartbeat_interval", "0.5"]
    cmd += [os.path.join(REPO, "tools", "chaos_drill.py"), "--worker"]
    res = subprocess.run(cmd, cwd=REPO, env=env, timeout=timeout,
                         stdout=subprocess.PIPE,
                         stderr=subprocess.STDOUT)
    return res, _trajectory(outp)


def _compare(name: str, base: dict, got: dict, steps: int,
             atol: float = 1e-10):
    missing = [s for s in range(steps) if s not in got]
    if missing:
        return f"{name}: steps never recorded: {missing[:10]}"
    for s in range(steps):
        d = abs(base[s] - got[s])
        if not (d <= atol):
            return (f"{name}: loss diverged at step {s}: baseline "
                    f"{base[s]!r} vs {got[s]!r} (|d|={d:g})")
    return None


def run_drill(steps: int, full: bool, keep_logs: bool = False) -> int:
    root = tempfile.mkdtemp(prefix="chaos_drill_")
    failures = []
    t0 = time.time()

    def scenario(name: str, spec: str, hang: bool = False):
        sdir = os.path.join(root, name.replace("@", "_").replace(":", "_"))
        os.makedirs(sdir, exist_ok=True)
        t = time.time()
        res, traj = _launch(sdir, steps, spec, hang)
        dt = time.time() - t
        err = None
        if res.returncode != 0:
            err = f"{name}: launcher rc={res.returncode}"
        else:
            err = _compare(name, baseline, traj, steps)
        if err is None and spec.startswith(("kill@", "crash_shard@")):
            # the killed leg must have left a readable black box
            err = _check_flight(sdir) or _check_telemetry(sdir)
            if err:
                err = f"{name}: {err}"
        tag = "FAIL" if err else "ok"
        print(f"[drill] {name:<24} {tag}  ({dt:.1f}s)", flush=True)
        if err:
            failures.append(err)
            tail = res.stdout.decode(errors="replace")[-2000:]
            print(tail, flush=True)
        elif keep_logs:
            print(res.stdout.decode(errors="replace")[-800:], flush=True)
        return res, traj

    # baseline: uninterrupted run
    bdir = os.path.join(root, "baseline")
    os.makedirs(bdir)
    res, baseline = _launch(bdir, steps, "", hang_watch=False)
    if res.returncode != 0 or len(baseline) != steps:
        print(res.stdout.decode(errors="replace")[-3000:])
        print(f"[drill] baseline failed (rc={res.returncode}, "
              f"{len(baseline)}/{steps} steps)")
        return 2
    print(f"[drill] baseline: {steps} steps ok "
          f"({time.time() - t0:.1f}s)", flush=True)

    kill_phases = range(steps) if full else \
        sorted({0, 1, steps // 2, steps - 1})
    crash_phases = range(steps) if full else sorted({1, steps // 2})
    for s in kill_phases:
        scenario(f"kill@{s}", f"kill@{s}")
    for s in crash_phases:
        # die after 3 of the 9 shard files of a snapshot (w1:4, w2:4,
        # scalars in manifest) — squarely mid-save
        scenario(f"crash_shard@{s}", f"crash_shard@{s}:3")
    scenario(f"nan@{max(1, steps // 3)}",
             f"nan@{max(1, steps // 3)}:2")
    scenario(f"elastic_exit@{max(1, steps // 2)}",
             f"elastic_exit@{max(1, steps // 2)}")
    scenario(f"hb_stale@{max(1, steps // 2)}",
             f"hb_stale@{max(1, steps // 2)}", hang=True)

    # corrupt-newest: two legs with driver-side file damage in between —
    # resume must CRC-reject the newest snapshot and fall back
    for mode in ("truncate", "bitflip"):
        name = f"corrupt_{mode}"
        sdir = os.path.join(root, name)
        os.makedirs(sdir, exist_ok=True)
        leg1 = steps // 2
        res, _ = _launch(sdir, leg1, "", hang_watch=False)
        if res.returncode != 0:
            failures.append(f"{name}: leg1 rc={res.returncode}")
            continue
        ckpt = os.path.join(sdir, "ckpt")
        with open(os.path.join(ckpt, "LATEST")) as f:
            newest = os.path.join(ckpt, f.read().strip())
        # the corruptors pull in paddle_tpu (and transitively jax) into
        # the DRIVER process — pin CPU first, unconditionally, per the
        # CLAUDE.md tunnel trap
        from paddle_tpu.device import pin_cpu
        pin_cpu(1)
        from paddle_tpu.testing import faults as fmod
        if mode == "truncate":
            fmod.truncate_shard(newest, index=0)
        else:
            fmod.bitflip_shard(newest, index=0)
        res, traj = _launch(sdir, steps, "", hang_watch=False)
        err = None
        if res.returncode != 0:
            err = f"{name}: leg2 rc={res.returncode}"
        else:
            err = _compare(name, baseline, traj, steps)
        print(f"[drill] {name:<24} {'FAIL' if err else 'ok'}", flush=True)
        if err:
            failures.append(err)
            print(res.stdout.decode(errors="replace")[-2000:], flush=True)

    dt = time.time() - t0
    if failures:
        print(f"[drill] {len(failures)} FAILURES in {dt:.1f}s:")
        for f in failures:
            print("  -", f)
        return 1
    print(f"[drill] ALL SCENARIOS PASSED ({steps}-step run, "
          f"full={full}) in {dt:.1f}s")
    return 0


# ====================================================== elastic scenarios
def run_elastic_drill(steps: int = 10, keep_logs: bool = False) -> int:
    """Device-loss-at-every-phase drill (ISSUE 14 acceptance): each
    scenario spawns the REAL launcher running the elastic GPT worker
    on the 8-virtual-device CPU mesh; the worker replays the
    post-replan trajectory from the restored checkpoint in-process and
    the driver asserts the summary + flight dump + telemetry."""
    import tempfile
    root = tempfile.mkdtemp(prefix="chaos_elastic_")
    failures = []
    t0 = time.time()

    def tele_doc(sdir):
        sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
        from telemetry_report import summarize
        return summarize(os.path.join(sdir, "telemetry.jsonl"))

    def scenario(name, spec, env=None, expect_replan=True,
                 require_elastic_block=True):
        sdir = os.path.join(root, name)
        os.makedirs(sdir, exist_ok=True)
        t = time.time()
        env = dict(env or {}, **{MODE_ENV: "elastic"})
        res, traj = _launch(sdir, steps, spec, hang_watch=False,
                            extra_env=env)
        dt = time.time() - t
        err = None
        summary = {}
        spath = os.path.join(sdir, "summary.json")
        if res.returncode != 0:
            err = f"launcher rc={res.returncode}"
        elif not os.path.exists(spath):
            err = "no summary.json from the worker"
        else:
            with open(spath) as f:
                summary = json.load(f)
        if err is None and expect_replan:
            if not summary.get("degraded"):
                err = f"run never degraded: {summary}"
            elif summary.get("world", 8) >= 8:
                err = f"world not reduced: {summary}"
            elif summary.get("trace_count") != 1:
                # zero recompiles after the replan warmup
                err = f"trace_count {summary.get('trace_count')} != 1"
            elif summary.get("restored_step") is None:
                err = "no reshard-restore anchor recorded"
            elif not summary.get("replay_identical"):
                err = (f"post-restore trajectory NOT bit-identical to "
                       f"a clean restore on the degraded plan: "
                       f"{summary.get('replay_mismatches')}")
        if err is None and not expect_replan:
            if summary.get("replans", 0) != 0 \
                    or summary.get("world") != 8:
                err = f"unexpected replan: {summary}"
        if err is None:
            # completeness from the trajectory file, not the summary —
            # an exit-101 scenario's pre-restart steps were recorded by
            # the FIRST process (out.jsonl spans restarts; the summary
            # is written by the last one)
            missing = [s for s in range(steps) if s not in traj]
            if missing:
                err = f"steps never recorded: {missing[:10]}"
        if err is None and expect_replan:
            err = _check_flight(sdir)
        if err is None:
            err = _check_telemetry(sdir)
        if err is None and require_elastic_block:
            doc = tele_doc(sdir)
            blk = doc.get("elastic") or {}
            if blk.get("replans", 0) < 1:
                err = (f"telemetry elastic block missing/empty: "
                       f"{blk} (train.elastic.* not surfaced)")
        tag = "FAIL" if err else "ok"
        print(f"[drill] elastic_{name:<18} {tag}  ({dt:.1f}s)",
              flush=True)
        if err:
            failures.append(f"elastic_{name}: {err}")
            print(res.stdout.decode(errors="replace")[-2500:],
                  flush=True)
        elif keep_logs:
            print(res.stdout.decode(errors="replace")[-800:],
                  flush=True)
        return traj, summary

    loss_at = steps // 2
    # baseline: the same worker, uninterrupted (for the straggler's
    # bit-identity check — replan scenarios compare against their OWN
    # clean-restore replay, not the 8-device baseline, because a
    # degraded plan legally reorders reductions)
    bdir = os.path.join(root, "baseline")
    os.makedirs(bdir)
    res, baseline = _launch(bdir, steps, "", hang_watch=False,
                            extra_env={MODE_ENV: "elastic"})
    if res.returncode != 0 or len(baseline) != steps:
        print(res.stdout.decode(errors="replace")[-3000:])
        print(f"[drill] elastic baseline failed (rc={res.returncode})")
        return 2
    print(f"[drill] elastic baseline: {steps} steps ok "
          f"({time.time() - t0:.1f}s)", flush=True)

    # the three kill phases
    scenario("midstep", f"device_loss@{loss_at}:1")
    scenario("midsave", f"device_loss@{loss_at}:1",
             env={ASYNC_ENV: "1"})
    scenario("midrestore",
             f"device_loss@{loss_at}:1,device_loss@{loss_at}:1")
    # collective hang -> watchdog -> replan
    scenario("hang", f"collective_hang@{loss_at}:30000",
             env={STEP_TO_ENV: "3"})
    # straggler within budget: NO replan, trajectory == baseline
    traj, _ = scenario("straggler", f"straggler@{loss_at}:500",
                       env={STEP_TO_ENV: "10"}, expect_replan=False,
                       require_elastic_block=False)
    err = _compare("elastic_straggler", baseline, traj, steps, atol=0.0)
    if err:
        failures.append(err)
    # exit-101 with a degraded world spec through the REAL launcher
    scenario("exit101", f"device_loss@{loss_at}:1",
             env={EXIT101_ENV: "1"}, require_elastic_block=False)

    dt = time.time() - t0
    if failures:
        print(f"[drill] {len(failures)} ELASTIC FAILURES in {dt:.1f}s:")
        for f in failures:
            print("  -", f)
        return 1
    print(f"[drill] ALL ELASTIC SCENARIOS PASSED ({steps}-step run) "
          f"in {dt:.1f}s")
    return 0


# =============================================================== gate mode
def run_serving_drill(keep_logs: bool = False) -> int:
    """The serving leg: tools/chaos_serving.py --quick in a fresh
    subprocess (it pins its own CPU device count before jax init, so
    it cannot share this process's backend)."""
    cmd = [sys.executable, os.path.join(REPO, "tools",
                                        "chaos_serving.py"), "--quick"]
    if keep_logs:
        cmd.append("--keep")
    t0 = time.time()
    res = subprocess.run(cmd, cwd=REPO, timeout=2400)
    tag = "ok" if res.returncode == 0 else "FAIL"
    print(f"[drill] serving_quick          {tag}  "
          f"({time.time() - t0:.1f}s)", flush=True)
    return res.returncode


def gate_main(steps: int, elastic_steps: int, tier1_log: str,
              keep_logs: bool = False) -> int:
    """The pre-commit robustness gate (CLAUDE.md testing section): ONE
    exit code = quick drill green AND elastic drill green AND the
    serving chaos drill green (chaos_serving.py --quick — autoscale/
    live-migration/device-loss scenarios included) AND the
    HLO-audit regression gate green (tools/audit_gate.py vs
    perf/audit_baseline.json — no new resharding) AND the
    compiled-memory gate green (tools/mem_gate.py vs
    perf/mem_baseline.json — no peak-HBM growth) AND
    tools/diff_failures.py clean against the stored tier-1 baseline
    (skipped with a note when no tier-1 log exists yet)."""
    rc = run_drill(steps, full=False, keep_logs=keep_logs)
    if rc != 0:
        print("[gate] quick drill FAILED", flush=True)
        return rc
    rc = run_elastic_drill(elastic_steps, keep_logs=keep_logs)
    if rc != 0:
        print("[gate] elastic drill FAILED", flush=True)
        return rc
    rc = run_serving_drill(keep_logs=keep_logs)
    if rc != 0:
        print("[gate] serving drill FAILED", flush=True)
        return rc
    res = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "audit_gate.py")],
        cwd=REPO)
    if res.returncode != 0:
        print("[gate] HLO audit gate FAILED (new resharding findings "
              "vs perf/audit_baseline.json)", flush=True)
        return res.returncode
    res = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "mem_gate.py")],
        cwd=REPO)
    if res.returncode != 0:
        print("[gate] compiled-memory gate FAILED (peak HBM grew vs "
              "perf/mem_baseline.json)", flush=True)
        return res.returncode
    if tier1_log and os.path.exists(tier1_log):
        res = subprocess.run(
            [sys.executable,
             os.path.join(REPO, "tools", "diff_failures.py"),
             tier1_log], cwd=REPO)
        if res.returncode != 0:
            print(f"[gate] diff_failures found NEW failures in "
                  f"{tier1_log}", flush=True)
            return res.returncode
    else:
        print(f"[gate] no tier-1 log at {tier1_log or '<unset>'}; "
              f"drills green — run the ROADMAP tier-1 command for the "
              f"full gate", flush=True)
    print("[gate] ROBUSTNESS GATE GREEN", flush=True)
    return 0


# ============================================================ bench mode
def bench_main(repeats: int = 5) -> int:
    """Measure checkpoint save/verify overhead (the BASELINE.md
    Robustness numbers) on the 8-virtual-device CPU mesh."""
    from paddle_tpu.device import pin_cpu
    assert pin_cpu(8), "could not pin the CPU platform"
    import numpy as np
    import jax
    import jax.numpy as jnp
    from paddle_tpu.parallel.mesh import build_mesh, use_mesh, \
        shard_value, P
    from paddle_tpu.parallel.checkpoint import (save_sharded,
                                                verify_checkpoint,
                                                CheckpointManager)

    mesh = build_mesh({"dp": 2, "mp": 4})
    rng = np.random.RandomState(0)
    with use_mesh(mesh):
        # ~8 MB of fp32 state: a model-scaled-down-but-not-trivial tree
        state = {
            "params": {
                "emb": shard_value(jnp.asarray(
                    rng.randn(1024, 512).astype(np.float32)),
                    P(None, "mp"), mesh),
                "w": shard_value(jnp.asarray(
                    rng.randn(512, 2048).astype(np.float32)),
                    P("mp", None), mesh),
            },
            "opt_state": {
                "m": shard_value(jnp.asarray(
                    rng.randn(1024, 512).astype(np.float32)),
                    P(None, "mp"), mesh),
            },
            "step": np.int64(1),
        }
        nbytes = (1024 * 512 * 2 + 512 * 2048) * 4
        with tempfile.TemporaryDirectory() as td:
            mgr = CheckpointManager(td, max_to_keep=3)
            save_ms, verify_ms = [], []
            for i in range(repeats):
                t = time.time()
                path = mgr.save(state, i)
                save_ms.append((time.time() - t) * 1e3)
                t = time.time()
                verify_checkpoint(path)
                verify_ms.append((time.time() - t) * 1e3)
        line = {
            "bench": "checkpoint_overhead",
            "state_mb": round(nbytes / 2 ** 20, 2),
            "save_ms_median": round(sorted(save_ms)[len(save_ms) // 2], 2),
            "verify_ms_median": round(
                sorted(verify_ms)[len(verify_ms) // 2], 2),
            "repeats": repeats,
        }
        print(json.dumps(line))
    return 0


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--worker", action="store_true",
                    help="internal: run as the training worker")
    ap.add_argument("--full", action="store_true",
                    help="kill/crash at EVERY step phase (slow)")
    ap.add_argument("--quick", action="store_true",
                    help="representative phases only (default)")
    ap.add_argument("--bench", action="store_true",
                    help="measure save/verify overhead, print one JSON")
    ap.add_argument("--elastic", action="store_true",
                    help="device-loss-at-every-phase scenario group "
                         "(ISSUE 14); composes with --quick")
    ap.add_argument("--gate", action="store_true",
                    help="pre-commit robustness gate: quick + elastic "
                         "+ serving drills AND tools/diff_failures.py "
                         "vs the stored tier-1 baseline, one exit code")
    ap.add_argument("--serving", action="store_true",
                    help="serving chaos drill only "
                         "(chaos_serving.py --quick subprocess)")
    ap.add_argument("--tier1-log", default="/tmp/_t1.log",
                    help="tier-1 pytest log for the --gate "
                         "diff_failures leg")
    ap.add_argument("--steps", type=int, default=30)
    ap.add_argument("--elastic-steps", type=int, default=10)
    ap.add_argument("--keep-logs", action="store_true")
    args = ap.parse_args()
    if args.worker:
        if os.environ.get(MODE_ENV) == "elastic":
            return elastic_worker_main()
        return worker_main()
    if args.bench:
        return bench_main()
    if args.gate:
        return gate_main(args.steps, args.elastic_steps,
                         args.tier1_log, keep_logs=args.keep_logs)
    if args.serving:
        return run_serving_drill(keep_logs=args.keep_logs)
    if args.elastic:
        rc = 0
        if args.quick or args.full:
            rc = run_drill(args.steps, full=args.full,
                           keep_logs=args.keep_logs)
        return rc or run_elastic_drill(args.elastic_steps,
                                       keep_logs=args.keep_logs)
    return run_drill(args.steps, full=args.full, keep_logs=args.keep_logs)


if __name__ == "__main__":
    sys.exit(main())
