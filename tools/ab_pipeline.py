"""A/B: SPMD scan pipeline vs host-driven 1F1B (VERDICT r4 item 6).

Races the two pipeline formulations on the virtual 8-device CPU mesh
(pp=4) with a transformer-block-shaped stage body, at interleave 1 and
2, checking gradient parity between them first. Writes the measured
table to perf/pipeline_ab.json; the shipped default follows the winner
(see parallel/pipeline.py + parallel/host_pipeline.py docstrings).

Run: python tools/ab_pipeline.py
"""
from __future__ import annotations

import functools
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

# unconditional CPU pin: the axon TPU plugin overrides the JAX_PLATFORMS
# env var, and a dead tunnel hangs backend init for minutes — this is a
# CPU-mesh A/B by design (CLAUDE.md environment traps; pin_cpu is the
# one shared workaround that also goes through the jax config API)
from paddle_tpu.device import pin_cpu

pin_cpu(8)

import jax
import jax.numpy as jnp
import numpy as np

P_STAGES = 4
D = 256
FFN = 1024
LAYERS_TOTAL = 8           # constant across interleave settings
M = 8                      # microbatches
MB = 4                     # rows per microbatch
S = 64


def stage_fn(chunk_params, x):
    """One transformer-ish block per chunk layer: x [mb, S, D]."""
    def body(h, lp):
        w1, b1, w2, b2 = lp
        h = h + jnp.tanh(h @ w1 + b1) @ w2 + b2
        return h, None
    x, _ = jax.lax.scan(body, x, chunk_params)
    return x


def make_params(n_chunks, key):
    ks = jax.random.split(key, 4)
    # same total model at every interleave: finer chunks, fewer layers each
    shape = (n_chunks, LAYERS_TOTAL // n_chunks)
    return (
        jax.random.normal(ks[0], shape + (D, FFN), jnp.float32) * 0.02,
        jnp.zeros(shape + (FFN,), jnp.float32),
        jax.random.normal(ks[1], shape + (FFN, D), jnp.float32) * 0.02,
        jnp.zeros(shape + (D,), jnp.float32),
    )


def loss_fn(y):
    return jnp.mean(jnp.square(y))


def run_spmd(mesh, params, x, interleave):
    from paddle_tpu.parallel.pipeline import pipeline_forward

    # dict-shaped params for parity with the host path
    pd = {"w1": params[0], "b1": params[1],
          "w2": params[2], "b2": params[3]}

    def sfn(chunk, h):
        return stage_fn((chunk["w1"], chunk["b1"], chunk["w2"],
                         chunk["b2"]), h)

    def step(pd, x_mb):
        y = pipeline_forward(sfn, pd, x_mb, P_STAGES, M,
                             mesh=mesh, interleave=interleave,
                             remat=True)
        return jnp.mean(jax.vmap(loss_fn)(y))

    g = jax.jit(jax.value_and_grad(step))
    x_mb = x.reshape((M, MB) + x.shape[1:])
    out = g(pd, x_mb)
    jax.block_until_ready(out)              # compile + warm
    t0 = time.perf_counter()
    for _ in range(5):
        out = g(pd, x_mb)
    jax.block_until_ready(out)
    dt = (time.perf_counter() - t0) / 5
    return float(out[0]), out[1], dt


def run_host(mesh, params, x, interleave):
    from paddle_tpu.parallel.host_pipeline import HostPipeline
    pd = {"w1": params[0], "b1": params[1],
          "w2": params[2], "b2": params[3]}

    def sfn(chunk, h):
        return stage_fn((chunk["w1"], chunk["b1"], chunk["w2"],
                         chunk["b2"]), h)

    pipe = HostPipeline(sfn, loss_fn, P_STAGES, M,
                        interleave=interleave, mesh=mesh)
    placed = pipe.place(pd)
    x_mb = x.reshape((M, MB) + x.shape[1:])
    out = pipe.grads(placed, x_mb)
    jax.block_until_ready(out)              # compile + warm
    t0 = time.perf_counter()
    for _ in range(5):
        out = pipe.grads(placed, x_mb)
    jax.block_until_ready(out)
    dt = (time.perf_counter() - t0) / 5
    return float(out[0]), pipe.gather_stacked(out[1]), dt


def main():
    from paddle_tpu.parallel.mesh import build_mesh
    mesh = build_mesh({"pp": P_STAGES})
    x = jax.random.normal(jax.random.PRNGKey(1), (M * MB, S, D),
                          jnp.float32)
    results = {}
    for v in (1, 2):
        params = make_params(P_STAGES * v, jax.random.PRNGKey(0))
        sl = st = None
        print(f"[ab] spmd v={v} compiling...", file=sys.stderr,
              flush=True)
        try:
            sl, sg, st = run_spmd(mesh, params, x, v)
            print(f"[ab] spmd v={v}: {st * 1e3:.1f} ms",
                  file=sys.stderr, flush=True)
        except ValueError as e:
            # ONLY the designed interleave>1 rejection is expected (the
            # A/B below is WHY it was removed); any other ValueError is
            # a real harness/pipeline break and must surface
            if "HostPipeline" not in str(e):
                raise
            print(f"[ab] spmd v={v} rejected: {e}", file=sys.stderr,
                  flush=True)
        print(f"[ab] host v={v} compiling...", file=sys.stderr,
              flush=True)
        hl, hg, ht = run_host(mesh, params, x, v)
        print(f"[ab] host v={v}: {ht * 1e3:.1f} ms", file=sys.stderr,
              flush=True)
        if sl is not None:
            # parity: same loss, same grads (host divides by m, spmd
            # means through vmap — both the mean-microbatch gradient)
            assert abs(sl - hl) < 1e-5, (sl, hl)
            for k in sg:
                np.testing.assert_allclose(np.asarray(sg[k]),
                                           np.asarray(hg[k]),
                                           rtol=1e-4, atol=1e-5)
        results[f"interleave{v}"] = {
            "spmd_ms": round(st * 1e3, 2) if st is not None
            else "rejected (interleave>1 removed from spmd_pipeline)",
            "host_ms": round(ht * 1e3, 2),
            "loss": round(hl, 6),
        }
        print(json.dumps({"interleave": v,
                          "spmd_ms": results[f"interleave{v}"]["spmd_ms"],
                          "host_ms": results[f"interleave{v}"]["host_ms"]}),
              flush=True)

    r1, r2 = results["interleave1"], results["interleave2"]
    results["notes"] = {
        "config": f"pp={P_STAGES} m={M} mb={MB} S={S} D={D} ffn={FFN}",
        "winner_v1": ("spmd" if isinstance(r1["spmd_ms"], float)
                      and r1["spmd_ms"] < r1["host_ms"] else "host"),
        "host_interleave_helps": r2["host_ms"] < r1["host_ms"],
        "historical_spmd_v2_ms": 2030.45,   # measured before removal
    }
    out_path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "perf", "pipeline_ab.json")
    with open(out_path, "w") as f:
        json.dump(results, f, indent=1)
    print(json.dumps(results["notes"]))


if __name__ == "__main__":
    main()
