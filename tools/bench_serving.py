"""Serving-engine benchmark: continuous batching vs sequential decode.

Measures aggregate generated tokens/sec on a mixed-prompt-length
workload two ways —
  (a) sequential per-request `greedy_generate` (the pre-engine serving
      story: each request prefills and decodes alone), and
  (b) the continuous-batching ServingEngine (inference/serving.py:
      slot-pool KV cache, bucketed prefill, one jitted decode tick)
— and prints ONE JSON line with both numbers, the speedup, and the
post-warmup trace counts (the zero-recompile acceptance observable).

Methodology: both paths run the full workload once to warm every
compiled executable (all prompt buckets + the decode step), then the
timed pass runs on warm caches. Work is step-sized per dispatch — each
engine tick advances every slot one token through one jit call, each
sequential step is a whole scan-fused generate — so per-call wall
timing is sound on the CPU rung (no tunnel in the loop; see
tools/bench_util.timeit's rule). The engine's per-tick host pull of
the sampled tokens is PART of the measured loop: that round trip is
the real serving cost, not an artifact.

Usage:
  python tools/bench_serving.py                # acceptance workload
  python tools/bench_serving.py --requests 32 --gen 64 --slots 16
  PADDLE_TPU_TELEMETRY_JSONL=serve.jsonl python tools/bench_serving.py

The default workload is the BASELINE.md "Serving" row: 16 requests,
prompt lengths uniform in [8, 96], 32 generated tokens each, GPT
2L x 128d, greedy.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

# CPU by default: the axon tunnel flaps and ANY backend init then hangs
# (CLAUDE.md trap). --tpu opts into the real backend.
if "--tpu" not in sys.argv:
    from paddle_tpu.device import pin_cpu
    pin_cpu(1)

import numpy as np                                    # noqa: E402
import jax                                            # noqa: E402
import jax.numpy as jnp                               # noqa: E402


def _log(msg):
    print(f"[bench_serving] {msg}", file=sys.stderr, flush=True)


def build_workload(n_requests, lo, hi, vocab, seed=0):
    rng = np.random.RandomState(seed)
    lens = rng.randint(lo, hi + 1, n_requests)
    return [rng.randint(0, vocab, L).astype(np.int32) for L in lens]


def run_sequential(params, cfg, prompts, gen, max_len, greedy_generate):
    for p in prompts:
        out = greedy_generate(params, jnp.asarray(p)[None], cfg, gen,
                              max_len=max_len)
    np.asarray(out)          # force the tail
    t0 = time.perf_counter()
    outs = []
    for p in prompts:
        out = greedy_generate(params, jnp.asarray(p)[None], cfg, gen,
                              max_len=max_len)
        outs.append(np.asarray(out)[0, len(p):])   # per-request pull —
        #                                the sequential loop's real shape
    return time.perf_counter() - t0, outs


def main():
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--requests", type=int, default=16)
    ap.add_argument("--gen", type=int, default=32)
    ap.add_argument("--slots", type=int, default=8)
    ap.add_argument("--prompt-lo", type=int, default=8)
    ap.add_argument("--prompt-hi", type=int, default=96)
    ap.add_argument("--family", choices=("gpt", "llama"), default="gpt")
    ap.add_argument("--layers", type=int, default=2)
    ap.add_argument("--hidden", type=int, default=128)
    ap.add_argument("--vocab", type=int, default=512)
    ap.add_argument("--max-len", type=int, default=0,
                    help="cache length (0 = next pow2 of hi+gen)")
    ap.add_argument("--tpu", action="store_true",
                    help="run on the default (TPU) backend")
    args = ap.parse_args()

    from paddle_tpu.models.decode import next_pow2
    from paddle_tpu.inference.serving import ServingEngine
    from paddle_tpu.profiler import monitor

    max_len = args.max_len or next_pow2(args.prompt_hi + args.gen)
    if args.family == "gpt":
        from paddle_tpu.models.gpt import (GPTConfig, init_gpt_params,
                                           greedy_generate)
        cfg = GPTConfig(vocab_size=args.vocab, hidden_size=args.hidden,
                        num_layers=args.layers,
                        num_heads=max(args.hidden // 32, 1),
                        max_seq_len=2 * max_len, sequence_parallel=False,
                        remat=False, dtype=jnp.float32)
        params = init_gpt_params(cfg, jax.random.PRNGKey(0))
    else:
        from paddle_tpu.models.llama import (LlamaConfig,
                                             init_llama_params,
                                             greedy_generate)
        cfg = LlamaConfig(vocab_size=args.vocab, hidden_size=args.hidden,
                          num_layers=args.layers,
                          num_heads=max(args.hidden // 32, 1),
                          num_kv_heads=max(args.hidden // 64, 1),
                          max_seq_len=2 * max_len, remat=False,
                          dtype=jnp.float32)
        params = init_llama_params(cfg, jax.random.PRNGKey(0))

    prompts = build_workload(args.requests, args.prompt_lo,
                             args.prompt_hi, args.vocab)
    total_tokens = args.requests * args.gen
    _log(f"workload: {args.requests} reqs, prompts "
         f"{args.prompt_lo}-{args.prompt_hi}, gen {args.gen}, "
         f"{args.family} {args.layers}Lx{args.hidden}d, "
         f"slots={args.slots}, max_len={max_len}")

    # ---- sequential per-request baseline (warm pass then timed pass)
    seq_s, seq_outs = run_sequential(params, cfg, prompts, args.gen,
                                     max_len, greedy_generate)
    seq_tps = total_tokens / seq_s
    _log(f"sequential: {seq_s * 1e3:.1f} ms total ({seq_tps:.1f} tok/s)")

    # ---- continuous batching: warm pass, then timed on warm traces
    tele_path = os.environ.get("PADDLE_TPU_TELEMETRY_JSONL")
    eng = ServingEngine(params, cfg, family=args.family,
                        num_slots=args.slots, max_len=max_len)
    eng.generate(prompts, args.gen)
    traces_warm = eng.trace_counts()
    if tele_path:
        monitor.registry().export_jsonl(tele_path)
    t0 = time.perf_counter()
    outs = eng.generate(prompts, args.gen)
    eng_s = time.perf_counter() - t0
    traces_after = eng.trace_counts()
    if tele_path:
        monitor.registry().export_jsonl(tele_path)
        eng.export_slo_jsonl(tele_path)    # TTFT / inter-token samples
        try:
            from telemetry_report import summarize
            _log("telemetry: " + json.dumps(
                summarize(tele_path).get("serving", {})))
        except Exception as e:
            _log(f"telemetry report failed: {e}")
    eng_tps = total_tokens / eng_s
    _log(f"engine: {eng_s * 1e3:.1f} ms total ({eng_tps:.1f} tok/s)")

    # correctness on the way out: greedy engine streams must equal the
    # per-request sequential ones token for token
    mismatches = sum(1 for a, b in zip(seq_outs, outs)
                     if not np.array_equal(a, b))
    recompiles = (traces_after[0] - traces_warm[0],
                  traces_after[1] - traces_warm[1])
    srv = {k[len("serving."):]: v for k, v in monitor.snapshot().items()
           if k.startswith("serving.")}
    print(json.dumps({
        "metric": "serving_tokens_per_sec",
        "value": round(eng_tps, 1),
        "unit": "tokens/s",
        "backend": jax.devices()[0].platform,
        "sequential_tokens_per_sec": round(seq_tps, 1),
        "speedup_vs_sequential": round(eng_tps / seq_tps, 2),
        "requests": args.requests, "gen": args.gen,
        "slots": args.slots, "family": args.family,
        "prompt_range": [args.prompt_lo, args.prompt_hi],
        "model": f"{args.layers}Lx{args.hidden}d",
        "recompiles_after_warmup": list(recompiles),
        "stream_mismatches": mismatches,
        "monitor": srv,
    }), flush=True)
    return 0 if mismatches == 0 else 1


if __name__ == "__main__":
    sys.exit(main())
