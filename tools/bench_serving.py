"""Serving-engine benchmark: continuous batching vs sequential decode.

Measures aggregate generated tokens/sec on a mixed-prompt-length
workload two ways —
  (a) sequential per-request `greedy_generate` (the pre-engine serving
      story: each request prefills and decodes alone), and
  (b) the continuous-batching ServingEngine (inference/serving.py:
      slot-pool KV cache, bucketed prefill, one jitted decode tick)
— and prints ONE JSON line with both numbers, the speedup, and the
post-warmup trace counts (the zero-recompile acceptance observable).

Methodology: both paths run the full workload once to warm every
compiled executable (all prompt buckets + the decode step), then the
timed pass runs on warm caches. Work is step-sized per dispatch — each
engine tick advances every slot one token through one jit call, each
sequential step is a whole scan-fused generate — so per-call wall
timing is sound on the CPU rung (no tunnel in the loop; see
tools/bench_util.timeit's rule). The engine's per-tick host pull of
the sampled tokens is PART of the measured loop: that round trip is
the real serving cost, not an artifact.

Usage:
  python tools/bench_serving.py                # acceptance workload
  python tools/bench_serving.py --requests 32 --gen 64 --slots 16
  python tools/bench_serving.py --capacity     # paged-vs-dense @ equal HBM
  PADDLE_TPU_TELEMETRY_JSONL=serve.jsonl python tools/bench_serving.py

The default workload is the BASELINE.md "Serving" row: 16 requests,
prompt lengths uniform in [8, 96], 32 generated tokens each, GPT
2L x 128d, greedy.

--capacity is the paged-KV acceptance bench (BASELINE.md "Serving
capacity"): at a FIXED page budget (the HBM of a --slots dense pool)
it measures (a) max concurrent streams and aggregate tokens/s for the
paged engine vs the dense engine on a shared-prefix workload (N
streams behind one long system prompt — the "millions of users" shape)
and (b) the kv-pool reuse stats (shared pages, shared prompt tokens,
COW copies). Streams must stay bit-identical to dense and post-warmup
recompiles zero.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

# CPU by default: the axon tunnel flaps and ANY backend init then hangs
# (CLAUDE.md trap). --tpu opts into the real backend.
if "--tpu" not in sys.argv:
    from paddle_tpu.device import pin_cpu
    pin_cpu(1)

import numpy as np                                    # noqa: E402
import jax                                            # noqa: E402
import jax.numpy as jnp                               # noqa: E402


def _log(msg):
    print(f"[bench_serving] {msg}", file=sys.stderr, flush=True)


def build_workload(n_requests, lo, hi, vocab, seed=0):
    rng = np.random.RandomState(seed)
    lens = rng.randint(lo, hi + 1, n_requests)
    return [rng.randint(0, vocab, L).astype(np.int32) for L in lens]


def run_sequential(params, cfg, prompts, gen, max_len, greedy_generate):
    for p in prompts:
        out = greedy_generate(params, jnp.asarray(p)[None], cfg, gen,
                              max_len=max_len)
    np.asarray(out)          # force the tail
    t0 = time.perf_counter()
    outs = []
    for p in prompts:
        out = greedy_generate(params, jnp.asarray(p)[None], cfg, gen,
                              max_len=max_len)
        outs.append(np.asarray(out)[0, len(p):])   # per-request pull —
        #                                the sequential loop's real shape
    return time.perf_counter() - t0, outs


def _drain_tracking_streams(eng):
    """Drain the engine, tracking the peak number of co-resident
    requests (active + mid-prefill slots) — the concurrency the pool
    actually sustained."""
    peak = 0
    while eng.has_work():
        eng.step()
        live = sum(1 for r in eng._slot_req if r is not None)
        peak = max(peak, live)
    return peak


def capacity_main(args):
    """--capacity: paged vs dense at EQUAL KV HBM on a shared-prefix
    workload. The page budget is what a dense pool of --slots slots
    occupies; the paged engine gets the same bytes and as many slots
    as requests. One JSON line."""
    from paddle_tpu.models.decode import next_pow2
    from paddle_tpu.inference.serving import ServingEngine
    from paddle_tpu.models.gpt import (GPTConfig, init_gpt_params)

    gen = args.gen
    sys_len, tail_lo, tail_hi = 96, 4, 12
    n_req = args.requests
    max_len = args.max_len or next_pow2(sys_len + tail_hi + gen)
    page_size = 16
    cfg = GPTConfig(vocab_size=args.vocab, hidden_size=args.hidden,
                    num_layers=args.layers,
                    num_heads=max(args.hidden // 32, 1),
                    max_seq_len=2 * max_len, sequence_parallel=False,
                    remat=False, dtype=jnp.float32)
    params = init_gpt_params(cfg, jax.random.PRNGKey(0))

    rng = np.random.RandomState(0)
    system = rng.randint(0, args.vocab, sys_len).astype(np.int32)
    prompts = [np.concatenate([
        system, rng.randint(0, args.vocab,
                            rng.randint(tail_lo, tail_hi + 1))
        .astype(np.int32)]) for _ in range(n_req)]
    total_tokens = n_req * gen

    # equal-HBM budget: the dense pool's pages (+1 scratch page, the
    # paged layout's only fixed overhead)
    budget = args.slots * (max_len // page_size) + 1
    _log(f"capacity workload: {n_req} reqs, system prompt {sys_len} + "
         f"tail {tail_lo}-{tail_hi}, gen {gen}, page budget {budget} "
         f"pages x {page_size} (= {args.slots} dense slots @ "
         f"max_len {max_len})")

    def run(eng):
        reqs = [eng.submit(p, gen) for p in prompts]
        peak = _drain_tracking_streams(eng)
        outs = [np.asarray(r.tokens, np.int32) for r in reqs]
        return peak, outs

    # dense at the budget: exactly --slots concurrent streams fit
    dense = ServingEngine(params, cfg, family=args.family,
                          num_slots=args.slots, max_len=max_len)
    run(dense)                                     # warm
    t0 = time.perf_counter()
    dense_peak, dense_outs = run(dense)
    dense_s = time.perf_counter() - t0
    dense_traces = dense.trace_counts()

    # paged at the SAME budget: slots are no longer the capacity
    # limit — the pool is
    paged = ServingEngine(params, cfg, family=args.family,
                          num_slots=n_req, max_len=max_len,
                          kv_layout="paged", page_size=page_size,
                          num_pages=budget, prefill_chunk=64)
    run(paged)                                     # warm
    traces_warm = paged.trace_counts()
    t0 = time.perf_counter()
    paged_peak, paged_outs = run(paged)
    paged_s = time.perf_counter() - t0
    traces_after = paged.trace_counts()
    pool = paged.pool_stats()

    mismatches = sum(1 for a, b in zip(dense_outs, paged_outs)
                     if not np.array_equal(a, b))
    dense_tps = total_tokens / dense_s
    paged_tps = total_tokens / paged_s
    print(json.dumps({
        "metric": "serving_capacity_streams",
        "value": paged_peak,
        "unit": "concurrent streams @ equal KV HBM",
        "backend": jax.devices()[0].platform,
        "dense_streams": dense_peak,
        "capacity_ratio": round(paged_peak / max(dense_peak, 1), 2),
        "paged_tokens_per_sec": round(paged_tps, 1),
        "dense_tokens_per_sec": round(dense_tps, 1),
        "throughput_ratio": round(paged_tps / dense_tps, 2),
        "page_budget": budget, "page_size": page_size,
        "requests": n_req, "gen": gen,
        "system_prompt": sys_len,
        "model": f"{args.layers}Lx{args.hidden}d",
        "family": args.family, "max_len": max_len,
        "recompiles_after_warmup": [
            traces_after[0] - traces_warm[0],
            traces_after[1] - traces_warm[1]],
        "stream_mismatches": mismatches,
        "pool": pool,
    }), flush=True)
    ok = (mismatches == 0 and paged_peak >= 2 * dense_peak
          and traces_after == traces_warm)
    return 0 if ok else 1


def chunk_slo_main(args):
    """--chunk-slo: the chunked-prefill SLO acceptance (BASELINE.md
    "Serving capacity"): inter-token latency percentiles of co-batched
    decode streams WHILE a near-max-length prompt joins mid-decode,
    monolithic suffix prefill vs chunked. The p99/max gap is the stall
    the interleave removes. One JSON line."""
    from paddle_tpu.models.decode import next_pow2
    from paddle_tpu.inference.serving import ServingEngine
    from paddle_tpu.models.gpt import GPTConfig, init_gpt_params

    gen = args.gen
    # defaults scaled UP vs the throughput bench: the stall only shows
    # when a monolithic prefill (quadratic in prompt length) costs many
    # decode ticks — a 2L x 128d model prefills 1k tokens in ~2 ticks
    max_len = args.max_len or max(next_pow2(96 + gen), 2048)
    hidden = args.hidden if args.hidden != 128 else 512
    layers = args.layers
    long_len = max_len - gen - 1            # near-max-length joiner
    cfg = GPTConfig(vocab_size=args.vocab, hidden_size=hidden,
                    num_layers=layers,
                    num_heads=max(hidden // 32, 1),
                    max_seq_len=2 * max_len, sequence_parallel=False,
                    remat=False, dtype=jnp.float32)
    params = init_gpt_params(cfg, jax.random.PRNGKey(0))
    rng = np.random.RandomState(0)
    short = [rng.randint(0, args.vocab, L).astype(np.int32)
             for L in rng.randint(8, 24, 3)]
    long_p = rng.randint(0, args.vocab, long_len).astype(np.int32)

    def run(chunk):
        # sharing OFF: the warm pass would otherwise cache the long
        # prompt's pages and the measured join would prefill ~nothing
        # (the right behavior in production, but this mode measures
        # the chunking of a REAL prefill)
        eng = ServingEngine(params, cfg, family=args.family,
                            num_slots=4, max_len=max_len,
                            kv_layout="paged", page_size=16,
                            prefill_chunk=chunk, prefix_sharing=False)
        eng.generate(short + [long_p], 4)          # warm every bucket
        srt = [eng.submit(p, gen) for p in short]
        for _ in range(4):                         # streams mid-decode
            eng.step()
        # measure the co-batched streams' inter-token latency INSIDE
        # the joiner's prefill window (submit -> its first token) —
        # the stall chunking bounds; steady-state ticks outside the
        # window would drown it
        eng._slo_itl.clear()
        lr = eng.submit(long_p, 4)
        while not lr.tokens and not lr.done and eng.has_work():
            eng.step()
        itl = sorted(eng.slo_snapshot()["itl_ms"])
        eng.drain()
        import math as m
        pct = lambda q: itl[max(0, m.ceil(q / 100 * len(itl)) - 1)]  # noqa: E731
        return ({"p50_ms": round(pct(50), 2), "p99_ms": round(pct(99), 2),
                 "max_ms": round(itl[-1], 2), "n": len(itl)},
                all(r.finish_reason in ("length", "eos") for r in srt))

    mono, ok_m = run(0)
    chunked, ok_c = run(64)
    print(json.dumps({
        "metric": "serving_chunked_prefill_itl_p99",
        "value": chunked["p99_ms"],
        "unit": "ms inter-token p99 while a max-length prompt prefills",
        "backend": jax.devices()[0].platform,
        "monolithic": mono, "chunked": chunked,
        "stall_reduction_max":
            round(mono["max_ms"] / chunked["max_ms"], 2),
        "long_prompt": long_len, "prefill_chunk": 64,
        "model": f"{layers}Lx{hidden}d",
        "all_resolved": bool(ok_m and ok_c),
    }), flush=True)
    return 0


def main():
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--requests", type=int, default=16)
    ap.add_argument("--gen", type=int, default=32)
    ap.add_argument("--slots", type=int, default=8)
    ap.add_argument("--prompt-lo", type=int, default=8)
    ap.add_argument("--prompt-hi", type=int, default=96)
    ap.add_argument("--family", choices=("gpt", "llama"), default="gpt")
    ap.add_argument("--layers", type=int, default=2)
    ap.add_argument("--hidden", type=int, default=128)
    ap.add_argument("--vocab", type=int, default=512)
    ap.add_argument("--max-len", type=int, default=0,
                    help="cache length (0 = next pow2 of hi+gen)")
    ap.add_argument("--tpu", action="store_true",
                    help="run on the default (TPU) backend")
    ap.add_argument("--capacity", action="store_true",
                    help="paged-vs-dense capacity bench at equal KV HBM")
    ap.add_argument("--chunk-slo", action="store_true",
                    help="inter-token p99 while a max-length prompt "
                         "prefills: monolithic vs chunked")
    args = ap.parse_args()
    if args.capacity:
        return capacity_main(args)
    if args.chunk_slo:
        return chunk_slo_main(args)

    from paddle_tpu.models.decode import next_pow2
    from paddle_tpu.inference.serving import ServingEngine
    from paddle_tpu.profiler import monitor

    max_len = args.max_len or next_pow2(args.prompt_hi + args.gen)
    if args.family == "gpt":
        from paddle_tpu.models.gpt import (GPTConfig, init_gpt_params,
                                           greedy_generate)
        cfg = GPTConfig(vocab_size=args.vocab, hidden_size=args.hidden,
                        num_layers=args.layers,
                        num_heads=max(args.hidden // 32, 1),
                        max_seq_len=2 * max_len, sequence_parallel=False,
                        remat=False, dtype=jnp.float32)
        params = init_gpt_params(cfg, jax.random.PRNGKey(0))
    else:
        from paddle_tpu.models.llama import (LlamaConfig,
                                             init_llama_params,
                                             greedy_generate)
        cfg = LlamaConfig(vocab_size=args.vocab, hidden_size=args.hidden,
                          num_layers=args.layers,
                          num_heads=max(args.hidden // 32, 1),
                          num_kv_heads=max(args.hidden // 64, 1),
                          max_seq_len=2 * max_len, remat=False,
                          dtype=jnp.float32)
        params = init_llama_params(cfg, jax.random.PRNGKey(0))

    prompts = build_workload(args.requests, args.prompt_lo,
                             args.prompt_hi, args.vocab)
    total_tokens = args.requests * args.gen
    _log(f"workload: {args.requests} reqs, prompts "
         f"{args.prompt_lo}-{args.prompt_hi}, gen {args.gen}, "
         f"{args.family} {args.layers}Lx{args.hidden}d, "
         f"slots={args.slots}, max_len={max_len}")

    # ---- sequential per-request baseline (warm pass then timed pass)
    seq_s, seq_outs = run_sequential(params, cfg, prompts, args.gen,
                                     max_len, greedy_generate)
    seq_tps = total_tokens / seq_s
    _log(f"sequential: {seq_s * 1e3:.1f} ms total ({seq_tps:.1f} tok/s)")

    # ---- continuous batching: warm pass, then timed on warm traces
    tele_path = os.environ.get("PADDLE_TPU_TELEMETRY_JSONL")
    eng = ServingEngine(params, cfg, family=args.family,
                        num_slots=args.slots, max_len=max_len)
    eng.generate(prompts, args.gen)
    traces_warm = eng.trace_counts()
    if tele_path:
        monitor.registry().export_jsonl(tele_path)
    t0 = time.perf_counter()
    outs = eng.generate(prompts, args.gen)
    eng_s = time.perf_counter() - t0
    traces_after = eng.trace_counts()
    if tele_path:
        monitor.registry().export_jsonl(tele_path)
        eng.export_slo_jsonl(tele_path)    # TTFT / inter-token samples
        try:
            from telemetry_report import summarize
            _log("telemetry: " + json.dumps(
                summarize(tele_path).get("serving", {})))
        except Exception as e:
            _log(f"telemetry report failed: {e}")
    eng_tps = total_tokens / eng_s
    _log(f"engine: {eng_s * 1e3:.1f} ms total ({eng_tps:.1f} tok/s)")

    # correctness on the way out: greedy engine streams must equal the
    # per-request sequential ones token for token
    mismatches = sum(1 for a, b in zip(seq_outs, outs)
                     if not np.array_equal(a, b))
    recompiles = (traces_after[0] - traces_warm[0],
                  traces_after[1] - traces_warm[1])
    srv = {k[len("serving."):]: v for k, v in monitor.snapshot().items()
           if k.startswith("serving.")}
    print(json.dumps({
        "metric": "serving_tokens_per_sec",
        "value": round(eng_tps, 1),
        "unit": "tokens/s",
        "backend": jax.devices()[0].platform,
        "sequential_tokens_per_sec": round(seq_tps, 1),
        "speedup_vs_sequential": round(eng_tps / seq_tps, 2),
        "requests": args.requests, "gen": args.gen,
        "slots": args.slots, "family": args.family,
        "prompt_range": [args.prompt_lo, args.prompt_hi],
        "model": f"{args.layers}Lx{args.hidden}d",
        "recompiles_after_warmup": list(recompiles),
        "stream_mismatches": mismatches,
        "monitor": srv,
    }), flush=True)
    return 0 if mismatches == 0 else 1


if __name__ == "__main__":
    sys.exit(main())
