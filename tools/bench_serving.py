"""Serving-engine benchmark: continuous batching vs sequential decode.

Measures aggregate generated tokens/sec on a mixed-prompt-length
workload two ways —
  (a) sequential per-request `greedy_generate` (the pre-engine serving
      story: each request prefills and decodes alone), and
  (b) the continuous-batching ServingEngine (inference/serving.py:
      slot-pool KV cache, bucketed prefill, one jitted decode tick)
— and prints ONE JSON line with both numbers, the speedup, and the
post-warmup trace counts (the zero-recompile acceptance observable).

Methodology: both paths run the full workload once to warm every
compiled executable (all prompt buckets + the decode step), then the
timed pass runs on warm caches. Work is step-sized per dispatch — each
engine tick advances every slot one token through one jit call, each
sequential step is a whole scan-fused generate — so per-call wall
timing is sound on the CPU rung (no tunnel in the loop; see
tools/bench_util.timeit's rule). The engine's per-tick host pull of
the sampled tokens is PART of the measured loop: that round trip is
the real serving cost, not an artifact.

Usage:
  python tools/bench_serving.py                # acceptance workload
  python tools/bench_serving.py --requests 32 --gen 64 --slots 16
  python tools/bench_serving.py --capacity     # paged-vs-dense @ equal HBM
  python tools/bench_serving.py --spec         # speculative A/B (1 slot)
  python tools/bench_serving.py --spec --sweep # acceptance vs gamma/K
  python tools/bench_serving.py --quant        # weight-only int8 A/B
  python tools/bench_serving.py --tp 2         # tp-sharded decode parity
  python tools/bench_serving.py --router 2     # replicated-engine router
  python tools/bench_serving.py --multi-tick 4 # fused K-tick decode A/B
  python tools/bench_serving.py --role-split   # prefill/decode disagg A/B
  python tools/bench_serving.py --autoscale-overhead  # control-loop A/B
  PADDLE_TPU_TELEMETRY_JSONL=serve.jsonl python tools/bench_serving.py

--tp N shards the decode tick over an N-way virtual-CPU build_mesh
('tp' axis — inference/serving.py mesh=): bit-parity vs the unsharded
engine, sharding specs asserted on the live engine, zero recompiles
after warmup. The CPU rung proves MECHANICS; tp wall-clock wins need
real chips (parallel.planner.plan_serving_tp prices when). --router R
races R replicated engines (inference/router.py least-loaded
admission) against one engine on a concurrency-limited workload —
near-linear aggregate tokens/s at R=2 is the BASELINE.md "Sharded
serving" acceptance bar. Both modes pin the virtual-CPU platform
UNCONDITIONALLY before jax init (CLAUDE.md tunnel trap: build_mesh
touches jax.devices()).

--spec is the speculative-decoding acceptance bench (BASELINE.md
"Speculative decoding"): SINGLE-STREAM (num_slots=1) greedy decode,
non-spec engine vs spec engine (inference/spec_decode.py), same
workload, warm traces, bit-parity asserted on the way out. Tunnel
safety per CLAUDE.md: each tick is one step-sized dispatch + one host
pull, and the spec win is precisely FEWER ticks for the same tokens —
the per-tick round trip is the real serving cost, so per-call wall
timing measures the thing being optimized on CPU and TPU alike.
Self-draft depth defaults to the FULL stack (draft == target,
acceptance 1.0): bench params are random-init, so a truncated draft
has no learned signal and the full-depth ceiling is what isolates the
ENGINE mechanics; --sweep additionally races truncated depths and
reports their acceptance. --adopt writes the evidence-gated registry
row ("spec_decode" -> "spec") only when the measured speedup clears
1.5x and the per-tick timing passes the roofline gate.

The default workload is the BASELINE.md "Serving" row: 16 requests,
prompt lengths uniform in [8, 96], 32 generated tokens each, GPT
2L x 128d, greedy.

--capacity is the paged-KV acceptance bench (BASELINE.md "Serving
capacity"): at a FIXED page budget (the HBM of a --slots dense pool)
it measures (a) max concurrent streams and aggregate tokens/s for the
paged engine vs the dense engine on a shared-prefix workload (N
streams behind one long system prompt — the "millions of users" shape)
and (b) the kv-pool reuse stats (shared pages, shared prompt tokens,
COW copies). Streams must stay bit-identical to dense and post-warmup
recompiles zero.
"""
from __future__ import annotations

import argparse
import json
import math
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

# CPU by default: the axon tunnel flaps and ANY backend init then hangs
# (CLAUDE.md trap). --tpu opts into the real backend — EXCEPT for the
# mesh-building modes (--tp), which pin the virtual-CPU platform
# UNCONDITIONALLY before any jax init: build_mesh touches jax.devices(),
# and a tunnel flap there hangs for minutes with no timeout in the
# loop (the bench_serving tp rung is a CPU-mesh parity/mechanics bench;
# TPU tp numbers come from the tpu_campaign harness, which owns its own
# timeouts).


def _argv_int(flag: str, default: int = 0) -> int:
    """Pre-argparse scan: the pin must happen before jax initializes,
    which is before argparse can run."""
    for i, a in enumerate(sys.argv):
        if a == flag and i + 1 < len(sys.argv):
            try:
                return int(sys.argv[i + 1])
            except ValueError:
                return default
        if a.startswith(flag + "="):
            try:
                return int(a.split("=", 1)[1])
            except ValueError:
                return default
    return default


_TP = max(_argv_int("--tp"), 1)
if _TP > 1 or "--tpu" not in sys.argv:
    from paddle_tpu.device import pin_cpu
    pin_cpu(_TP)

import numpy as np                                    # noqa: E402
import jax                                            # noqa: E402
import jax.numpy as jnp                               # noqa: E402


def _log(msg):
    print(f"[bench_serving] {msg}", file=sys.stderr, flush=True)


def build_workload(n_requests, lo, hi, vocab, seed=0):
    rng = np.random.RandomState(seed)
    lens = rng.randint(lo, hi + 1, n_requests)
    return [rng.randint(0, vocab, L).astype(np.int32) for L in lens]


def run_sequential(params, cfg, prompts, gen, max_len, greedy_generate):
    for p in prompts:
        out = greedy_generate(params, jnp.asarray(p)[None], cfg, gen,
                              max_len=max_len)
    np.asarray(out)          # force the tail
    t0 = time.perf_counter()
    outs = []
    for p in prompts:
        out = greedy_generate(params, jnp.asarray(p)[None], cfg, gen,
                              max_len=max_len)
        outs.append(np.asarray(out)[0, len(p):])   # per-request pull —
        #                                the sequential loop's real shape
    return time.perf_counter() - t0, outs


def _drain_tracking_streams(eng):
    """Drain the engine, tracking the peak number of co-resident
    requests (active + mid-prefill slots) — the concurrency the pool
    actually sustained."""
    peak = 0
    while eng.has_work():
        eng.step()
        live = sum(1 for r in eng._slot_req if r is not None)
        peak = max(peak, live)
    return peak


def capacity_main(args):
    """--capacity: paged vs dense at EQUAL KV HBM on a shared-prefix
    workload. The page budget is what a dense pool of --slots slots
    occupies; the paged engine gets the same bytes and as many slots
    as requests. One JSON line."""
    from paddle_tpu.models.decode import next_pow2
    from paddle_tpu.inference.serving import ServingEngine
    from paddle_tpu.models.gpt import (GPTConfig, init_gpt_params)

    gen = args.gen
    sys_len, tail_lo, tail_hi = 96, 4, 12
    n_req = args.requests
    max_len = args.max_len or next_pow2(sys_len + tail_hi + gen)
    page_size = 16
    cfg = GPTConfig(vocab_size=args.vocab, hidden_size=args.hidden,
                    num_layers=args.layers,
                    num_heads=max(args.hidden // 32, 1),
                    max_seq_len=2 * max_len, sequence_parallel=False,
                    remat=False, dtype=jnp.float32)
    params = init_gpt_params(cfg, jax.random.PRNGKey(0))

    rng = np.random.RandomState(0)
    system = rng.randint(0, args.vocab, sys_len).astype(np.int32)
    prompts = [np.concatenate([
        system, rng.randint(0, args.vocab,
                            rng.randint(tail_lo, tail_hi + 1))
        .astype(np.int32)]) for _ in range(n_req)]
    total_tokens = n_req * gen

    # equal-HBM budget: the dense pool's pages (+1 scratch page, the
    # paged layout's only fixed overhead)
    budget = args.slots * (max_len // page_size) + 1
    _log(f"capacity workload: {n_req} reqs, system prompt {sys_len} + "
         f"tail {tail_lo}-{tail_hi}, gen {gen}, page budget {budget} "
         f"pages x {page_size} (= {args.slots} dense slots @ "
         f"max_len {max_len})")

    def run(eng):
        reqs = [eng.submit(p, gen) for p in prompts]
        peak = _drain_tracking_streams(eng)
        outs = [np.asarray(r.tokens, np.int32) for r in reqs]
        return peak, outs

    # dense at the budget: exactly --slots concurrent streams fit
    dense = ServingEngine(params, cfg, family=args.family,
                          num_slots=args.slots, max_len=max_len)
    run(dense)                                     # warm
    t0 = time.perf_counter()
    dense_peak, dense_outs = run(dense)
    dense_s = time.perf_counter() - t0
    dense_traces = dense.trace_counts()

    # paged at the SAME budget: slots are no longer the capacity
    # limit — the pool is
    paged = ServingEngine(params, cfg, family=args.family,
                          num_slots=n_req, max_len=max_len,
                          kv_layout="paged", page_size=page_size,
                          num_pages=budget, prefill_chunk=64)
    run(paged)                                     # warm
    traces_warm = paged.trace_counts()
    t0 = time.perf_counter()
    paged_peak, paged_outs = run(paged)
    paged_s = time.perf_counter() - t0
    traces_after = paged.trace_counts()
    pool = paged.pool_stats()

    mismatches = sum(1 for a, b in zip(dense_outs, paged_outs)
                     if not np.array_equal(a, b))
    dense_tps = total_tokens / dense_s
    paged_tps = total_tokens / paged_s
    print(json.dumps({
        "metric": "serving_capacity_streams",
        "value": paged_peak,
        "unit": "concurrent streams @ equal KV HBM",
        "backend": jax.devices()[0].platform,
        "dense_streams": dense_peak,
        "capacity_ratio": round(paged_peak / max(dense_peak, 1), 2),
        "paged_tokens_per_sec": round(paged_tps, 1),
        "dense_tokens_per_sec": round(dense_tps, 1),
        "throughput_ratio": round(paged_tps / dense_tps, 2),
        "page_budget": budget, "page_size": page_size,
        "requests": n_req, "gen": gen,
        "system_prompt": sys_len,
        "model": f"{args.layers}Lx{args.hidden}d",
        "family": args.family, "max_len": max_len,
        "recompiles_after_warmup": [
            traces_after[0] - traces_warm[0],
            traces_after[1] - traces_warm[1]],
        "stream_mismatches": mismatches,
        "pool": pool,
    }), flush=True)
    ok = (mismatches == 0 and paged_peak >= 2 * dense_peak
          and traces_after == traces_warm)
    return 0 if ok else 1


def chunk_slo_main(args):
    """--chunk-slo: the chunked-prefill SLO acceptance (BASELINE.md
    "Serving capacity"): inter-token latency percentiles of co-batched
    decode streams WHILE a near-max-length prompt joins mid-decode,
    monolithic suffix prefill vs chunked. The p99/max gap is the stall
    the interleave removes. One JSON line."""
    from paddle_tpu.models.decode import next_pow2
    from paddle_tpu.inference.serving import ServingEngine
    from paddle_tpu.models.gpt import GPTConfig, init_gpt_params

    gen = args.gen
    # defaults scaled UP vs the throughput bench: the stall only shows
    # when a monolithic prefill (quadratic in prompt length) costs many
    # decode ticks — a 2L x 128d model prefills 1k tokens in ~2 ticks
    max_len = args.max_len or max(next_pow2(96 + gen), 2048)
    hidden = args.hidden if args.hidden != 128 else 512
    layers = args.layers
    long_len = max_len - gen - 1            # near-max-length joiner
    cfg = GPTConfig(vocab_size=args.vocab, hidden_size=hidden,
                    num_layers=layers,
                    num_heads=max(hidden // 32, 1),
                    max_seq_len=2 * max_len, sequence_parallel=False,
                    remat=False, dtype=jnp.float32)
    params = init_gpt_params(cfg, jax.random.PRNGKey(0))
    rng = np.random.RandomState(0)
    short = [rng.randint(0, args.vocab, L).astype(np.int32)
             for L in rng.randint(8, 24, 3)]
    long_p = rng.randint(0, args.vocab, long_len).astype(np.int32)

    def run(chunk):
        # sharing OFF: the warm pass would otherwise cache the long
        # prompt's pages and the measured join would prefill ~nothing
        # (the right behavior in production, but this mode measures
        # the chunking of a REAL prefill)
        eng = ServingEngine(params, cfg, family=args.family,
                            num_slots=4, max_len=max_len,
                            kv_layout="paged", page_size=16,
                            prefill_chunk=chunk, prefix_sharing=False)
        eng.generate(short + [long_p], 4)          # warm every bucket
        srt = [eng.submit(p, gen) for p in short]
        for _ in range(4):                         # streams mid-decode
            eng.step()
        # measure the co-batched streams' inter-token latency INSIDE
        # the joiner's prefill window (submit -> its first token) —
        # the stall chunking bounds; steady-state ticks outside the
        # window would drown it
        eng._slo_itl.clear()
        lr = eng.submit(long_p, 4)
        while not lr.tokens and not lr.done and eng.has_work():
            eng.step()
        itl = sorted(eng.slo_snapshot()["itl_ms"])
        eng.drain()
        import math as m
        pct = lambda q: itl[max(0, m.ceil(q / 100 * len(itl)) - 1)]  # noqa: E731
        return ({"p50_ms": round(pct(50), 2), "p99_ms": round(pct(99), 2),
                 "max_ms": round(itl[-1], 2), "n": len(itl)},
                all(r.finish_reason in ("length", "eos") for r in srt))

    mono, ok_m = run(0)
    chunked, ok_c = run(64)
    print(json.dumps({
        "metric": "serving_chunked_prefill_itl_p99",
        "value": chunked["p99_ms"],
        "unit": "ms inter-token p99 while a max-length prompt prefills",
        "backend": jax.devices()[0].platform,
        "monolithic": mono, "chunked": chunked,
        "stall_reduction_max":
            round(mono["max_ms"] / chunked["max_ms"], 2),
        "long_prompt": long_len, "prefill_chunk": 64,
        "model": f"{layers}Lx{hidden}d",
        "all_resolved": bool(ok_m and ok_c),
    }), flush=True)
    return 0


def spec_main(args):
    """--spec: single-stream speculative A/B. One JSON line with both
    tokens/s numbers, the speedup, acceptance rate, tick counts, and
    (with --sweep) the acceptance-vs-gamma/draft-depth table."""
    from paddle_tpu.models.decode import next_pow2
    from paddle_tpu.inference.serving import ServingEngine
    from paddle_tpu.profiler import monitor

    gen = args.gen
    max_len = args.max_len or next_pow2(args.prompt_hi + gen + args.gamma)
    if args.family == "gpt":
        from paddle_tpu.models.gpt import GPTConfig, init_gpt_params
        cfg = GPTConfig(vocab_size=args.vocab, hidden_size=args.hidden,
                        num_layers=args.layers,
                        num_heads=max(args.hidden // 32, 1),
                        max_seq_len=2 * max_len, sequence_parallel=False,
                        remat=False, dtype=jnp.float32)
        params = init_gpt_params(cfg, jax.random.PRNGKey(0))
    else:
        from paddle_tpu.models.llama import LlamaConfig, init_llama_params
        cfg = LlamaConfig(vocab_size=args.vocab, hidden_size=args.hidden,
                          num_layers=args.layers,
                          num_heads=max(args.hidden // 32, 1),
                          num_kv_heads=max(args.hidden // 64, 1),
                          max_seq_len=2 * max_len, remat=False,
                          dtype=jnp.float32)
        params = init_llama_params(cfg, jax.random.PRNGKey(0))
    kd = args.draft_layers or args.layers      # full depth = ceiling
    prompts = build_workload(args.requests, args.prompt_lo,
                             args.prompt_hi, args.vocab)
    total_tokens = args.requests * gen
    _log(f"spec workload: {args.requests} single streams x {gen} tok, "
         f"{args.family} {args.layers}Lx{args.hidden}d, gamma={args.gamma}, "
         f"draft_layers={kd}, max_len={max_len}")

    def run(eng):
        t0 = time.perf_counter()
        outs = eng.generate(prompts, gen)
        return time.perf_counter() - t0, outs

    def ticks():
        return monitor.counter("serving.decode_ticks").value

    base = ServingEngine(params, cfg, family=args.family, num_slots=1,
                         max_len=max_len)
    run(base)                                        # warm
    k0 = ticks()
    base_s, base_outs = run(base)
    base_ticks = ticks() - k0

    spec = ServingEngine(params, cfg, family=args.family, num_slots=1,
                         max_len=max_len, spec_decode="spec",
                         gamma=args.gamma, draft_layers=kd)
    run(spec)                                        # warm
    traces_warm = spec.trace_counts()
    k0 = ticks()
    spec_s, spec_outs = run(spec)
    spec_ticks = ticks() - k0
    traces_after = spec.trace_counts()

    mismatches = sum(1 for a, b in zip(base_outs, spec_outs)
                     if not np.array_equal(a, b))
    base_tps = total_tokens / base_s
    spec_tps = total_tokens / spec_s
    accept = (spec._spec_acc_total / spec._spec_prop_total
              if spec._spec_prop_total else 0.0)
    doc = {
        "metric": "serving_spec_tokens_per_sec",
        "value": round(spec_tps, 1),
        "unit": "single-stream tokens/s",
        "backend": jax.devices()[0].platform,
        "nonspec_tokens_per_sec": round(base_tps, 1),
        "speedup_vs_nonspec": round(spec_tps / base_tps, 2),
        "acceptance_rate": round(accept, 3),
        "gamma": args.gamma, "draft_layers": kd,
        "decode_ticks": [base_ticks, spec_ticks],
        "requests": args.requests, "gen": gen,
        "model": f"{args.layers}Lx{args.hidden}d",
        "family": args.family, "max_len": max_len,
        "recompiles_after_warmup": [
            traces_after[0] - traces_warm[0],
            traces_after[1] - traces_warm[1]],
        "stream_mismatches": mismatches,
    }

    if args.sweep:
        # acceptance vs (gamma, draft depth): random-init params give
        # truncated drafts no learned signal — the sweep documents the
        # graceful-degradation floor next to the full-depth ceiling
        table = []
        for g in (2, 4, 8):
            for k in sorted({1, max(1, args.layers // 2), args.layers}):
                e = ServingEngine(params, cfg, family=args.family,
                                  num_slots=1, max_len=max_len,
                                  spec_decode="spec", gamma=g,
                                  draft_layers=k)
                run(e)                               # warm
                dt, outs = run(e)
                bad = sum(1 for a, b in zip(base_outs, outs)
                          if not np.array_equal(a, b))
                acc = (e._spec_acc_total / e._spec_prop_total
                       if e._spec_prop_total else 0.0)
                table.append({"gamma": g, "draft_layers": k,
                              "acceptance_rate": round(acc, 3),
                              "tokens_per_sec":
                                  round(total_tokens / dt, 1),
                              "speedup":
                                  round(total_tokens / dt / base_tps, 2),
                              "stream_mismatches": bad})
                mismatches += bad      # sweep parity gates the exit too
        doc["sweep"] = table
        # the ONE JSON line must agree with the exit code: fold sweep
        # mismatches into the top-level count too (per-row counts stay
        # in the table)
        doc["stream_mismatches"] = mismatches

    if args.adopt:
        from paddle_tpu.kernels import registry
        ok = (mismatches == 0
              and doc["speedup_vs_nonspec"] >= 1.5
              and doc["recompiles_after_warmup"] == [0, 0])
        if not ok:
            doc["adopt"] = "refused: speedup/parity/recompile gate failed"
        else:
            # evidence: per-tick ms + the weight bytes a spec tick
            # streams (target pass over gamma+1 positions + gamma
            # truncated draft passes) — the roofline gate re-checks
            pbytes = sum(np.asarray(v).nbytes for v in params.values())
            per_tick_ms = spec_s * 1e3 / max(spec_ticks, 1)
            bytes_moved = pbytes * (1.0 + args.gamma * kd / args.layers)
            problem = registry.adopt(
                "spec_decode", "spec", per_tick_ms,
                bytes_moved=bytes_moved,
                source=(f"bench_serving --spec: {doc['speedup_vs_nonspec']}x "
                        f"single-stream GREEDY vs non-spec "
                        f"(gamma={args.gamma}, K={kd}, "
                        f"accept={doc['acceptance_rate']}; sampled-only "
                        "workloads were not measured — they pay the draft "
                        "with acceptance forced to 0)"))
            doc["adopt"] = problem or "adopted"
    print(json.dumps(doc), flush=True)
    return 0 if mismatches == 0 else 1


def quant_main(args):
    """--quant: weight-only int8 A/B (BASELINE.md "Quantized serving")
    — fp engine vs quant="int8" engine on the same workload, same
    slots. Reports tokens/s both ways, the weight-HBM bytes both ways
    (the halving observable), the logit max-abs-error budget from a
    prefill-shaped probe through both param trees, and the intra-quant
    determinism check (quant dense vs quant paged must be
    BIT-IDENTICAL — weight-only dequant is deterministic; only the
    quant-vs-fp comparison carries an error budget). --adopt writes
    the evidence-gated registry row ("quant_matmul" -> the measured
    impl) and refuses unless weight bytes <= 0.55x fp AND tokens/s
    >= 0.95x fp with zero recompiles and exact intra-quant parity.
    One JSON line."""
    from paddle_tpu.models.decode import next_pow2
    from paddle_tpu.inference.serving import ServingEngine
    from paddle_tpu.profiler import monitor

    gen = args.gen
    max_len = args.max_len or next_pow2(args.prompt_hi + gen)
    params, cfg = _build_family(args, max_len)
    prompts = build_workload(args.requests, args.prompt_lo,
                             args.prompt_hi, args.vocab)
    total_tokens = args.requests * gen
    _log(f"quant workload: {args.requests} reqs, gen {gen}, "
         f"{args.family} {args.layers}Lx{args.hidden}d, "
         f"slots={args.slots}, max_len={max_len}")

    def run(eng):
        t0 = time.perf_counter()
        outs = eng.generate(prompts, gen)
        return time.perf_counter() - t0, outs

    def ticks():
        return monitor.counter("serving.decode_ticks").value

    # quant="off" EXPLICITLY: after a successful --adopt the registry
    # winner would make the default "auto" quantize this baseline too,
    # and the A/B would silently compare quant vs quant forever after
    base = ServingEngine(params, cfg, family=args.family,
                         num_slots=args.slots, max_len=max_len,
                         quant="off")
    run(base)                                        # warm
    base_s, _base_outs = run(base)

    eng = ServingEngine(params, cfg, family=args.family,
                        num_slots=args.slots, max_len=max_len,
                        quant="int8")
    run(eng)                                         # warm
    traces_warm = eng.trace_counts()
    k0 = ticks()
    q_s, q_outs = run(eng)
    q_ticks = ticks() - k0
    traces_after = eng.trace_counts()

    # intra-quant determinism: the paged engine over the SAME int8
    # tree must stream bit-identically (the exact-parity tier)
    paged = ServingEngine(params, cfg, family=args.family,
                          num_slots=args.slots, max_len=max_len,
                          quant="int8", kv_layout="paged",
                          page_size=16)
    run(paged)                                       # warm
    _, paged_outs = run(paged)
    mismatches = sum(1 for a, b in zip(q_outs, paged_outs)
                     if not np.array_equal(a, b))

    # logit error budget: one prefill-shaped probe through both trees
    probe = jnp.asarray(prompts[0])[None]
    fam = eng.family
    lg_fp, _ = fam.forward_cached(
        params, probe, fam.init_cache(cfg, 1, probe.shape[1]), 0, cfg)
    lg_q, _ = fam.forward_cached(
        eng._params, probe, fam.init_cache(cfg, 1, probe.shape[1]), 0,
        cfg)
    err = float(jnp.max(jnp.abs(lg_fp.astype(jnp.float32)
                                - lg_q.astype(jnp.float32))))
    lg_span = float(jnp.max(jnp.abs(lg_fp.astype(jnp.float32))))

    st = eng.quant_stats()
    bytes_ratio = st["quant_bytes"] / st["fp_bytes"]
    base_tps = total_tokens / base_s
    q_tps = total_tokens / q_s
    recompiles = [traces_after[0] - traces_warm[0],
                  traces_after[1] - traces_warm[1]]
    doc = {
        "metric": "serving_quant_tokens_per_sec",
        "value": round(q_tps, 1),
        "unit": "tokens/s (weight-only int8)",
        "backend": jax.devices()[0].platform,
        "fp_tokens_per_sec": round(base_tps, 1),
        "tokens_ratio_vs_fp": round(q_tps / base_tps, 2),
        "fp_weight_bytes": st["fp_bytes"],
        "quant_weight_bytes": st["quant_bytes"],
        "weight_bytes_ratio": round(bytes_ratio, 3),
        "logit_max_abs_err": round(err, 5),
        "logit_max_abs": round(lg_span, 3),
        "quant_leaves": list(st["quant_leaf_names"]) + ["head"],
        "requests": args.requests, "gen": gen, "slots": args.slots,
        "model": f"{args.layers}Lx{args.hidden}d",
        "family": args.family, "max_len": max_len,
        "recompiles_after_warmup": recompiles,
        "stream_mismatches": mismatches,     # quant dense vs paged
    }

    if args.adopt:
        from paddle_tpu.kernels import registry
        from paddle_tpu.kernels.quant_matmul import matmul_impl
        ok = (mismatches == 0
              and bytes_ratio <= 0.55
              and doc["tokens_ratio_vs_fp"] >= 0.95
              and recompiles == [0, 0])
        if not ok:
            doc["adopt"] = ("refused: bytes/<=0.55x, tokens/s>=0.95x, "
                            "parity or recompile gate failed")
        else:
            # evidence: per-tick ms + the int8 weight bytes a decode
            # tick streams — the roofline gate re-checks plausibility
            per_tick_ms = q_s * 1e3 / max(q_ticks, 1)
            problem = registry.adopt(
                "quant_matmul", matmul_impl(), per_tick_ms,
                bytes_moved=float(st["quant_bytes"]),
                source=(f"bench_serving --quant: weight bytes "
                        f"{doc['weight_bytes_ratio']}x fp, tokens/s "
                        f"{doc['tokens_ratio_vs_fp']}x fp, logit "
                        f"max-abs-err {doc['logit_max_abs_err']} "
                        f"(|logit| max {doc['logit_max_abs']})"))
            doc["adopt"] = problem or "adopted"
    print(json.dumps(doc), flush=True)
    return 0 if mismatches == 0 else 1


def _build_family(args, max_len):
    """(params, cfg) for the bench family/shape at a given cache len —
    shared by the tp/router modes (the other modes predate it)."""
    if args.family == "gpt":
        from paddle_tpu.models.gpt import GPTConfig, init_gpt_params
        cfg = GPTConfig(vocab_size=args.vocab, hidden_size=args.hidden,
                        num_layers=args.layers,
                        num_heads=max(args.hidden // 32, 1),
                        max_seq_len=2 * max_len, sequence_parallel=False,
                        remat=False, dtype=jnp.float32)
        return init_gpt_params(cfg, jax.random.PRNGKey(0)), cfg
    from paddle_tpu.models.llama import LlamaConfig, init_llama_params
    cfg = LlamaConfig(vocab_size=args.vocab, hidden_size=args.hidden,
                      num_layers=args.layers,
                      num_heads=max(args.hidden // 32, 1),
                      num_kv_heads=max(args.hidden // 64, 1),
                      max_seq_len=2 * max_len, remat=False,
                      dtype=jnp.float32)
    return init_llama_params(cfg, jax.random.PRNGKey(0)), cfg


def tp_main(args):
    """--tp N: tensor-parallel decode tick on an N-way CPU mesh vs the
    unsharded engine — the BASELINE.md "Sharded serving" parity +
    mechanics rung. The CPU mesh measures MECHANICS (bit-parity, trace
    ceilings, one pull per tick); tp wall-clock WINS need real chips
    (the tick is weight-bandwidth bound — parallel.planner
    plan_serving_tp prices when tp pays). One JSON line."""
    from paddle_tpu.models.decode import next_pow2
    from paddle_tpu.inference.serving import ServingEngine
    from paddle_tpu.parallel.mesh import build_mesh
    from paddle_tpu.parallel.planner import plan_serving_tp

    gen = args.gen
    max_len = args.max_len or next_pow2(args.prompt_hi + gen)
    params, cfg = _build_family(args, max_len)
    prompts = build_workload(args.requests, args.prompt_lo,
                             args.prompt_hi, args.vocab)
    total_tokens = args.requests * gen
    mesh = build_mesh({"tp": args.tp})
    _log(f"tp workload: {args.requests} reqs, gen {gen}, "
         f"{args.family} {args.layers}Lx{args.hidden}d, tp={args.tp} "
         f"over {jax.device_count()} devices, "
         f"planner says {plan_serving_tp(cfg, args.tp)}")

    def run(eng):
        t0 = time.perf_counter()
        outs = eng.generate(prompts, gen)
        return time.perf_counter() - t0, outs

    def warm(eng):
        # warm to a FIXED POINT, not one pass: under the paged layout
        # with prefix sharing the SECOND run of the same prompts hits
        # the warm run's cached prefixes and takes the aligned-full-
        # match path (a prefill bucket the first pass never compiled),
        # so one warm run undercounts the steady-state executables
        run(eng)
        while True:
            before = eng.trace_counts()
            run(eng)
            if eng.trace_counts() == before:
                return

    base = ServingEngine(params, cfg, family=args.family,
                         num_slots=args.slots, max_len=max_len,
                         kv_layout=args.kv_layout)
    warm(base)
    base_s, base_outs = run(base)

    eng = ServingEngine(params, cfg, family=args.family,
                        num_slots=args.slots, max_len=max_len,
                        kv_layout=args.kv_layout, mesh=mesh)
    warm(eng)
    traces_warm = eng.trace_counts()
    tp_s, tp_outs = run(eng)
    traces_after = eng.trace_counts()

    mismatches = sum(1 for a, b in zip(base_outs, tp_outs)
                     if not np.array_equal(a, b))
    # the sharding contract, asserted on the live engine (the same
    # .sharding.spec checks the CPU-mesh test suite pins): params carry
    # the tp axis; the cache does too UNLESS the documented shape-aware
    # degrade applies (tp doesn't divide the KV heads — deep GQA — and
    # the pool legitimately replicates, kernels/decode_attention
    # cache_pspecs)
    kv_heads = getattr(cfg, "num_kv_heads", None) or cfg.num_heads
    cache_sharded = "tp" in str(eng._cache["k"].sharding.spec)
    shard_ok = (any("tp" in str(v.sharding.spec)
                    for v in eng._params.values())
                and (cache_sharded or kv_heads % args.tp != 0))
    print(json.dumps({
        "metric": "serving_tp_tokens_per_sec",
        "value": round(total_tokens / tp_s, 1),
        "unit": f"tokens/s @ tp={args.tp}",
        "backend": jax.devices()[0].platform,
        "unsharded_tokens_per_sec": round(total_tokens / base_s, 1),
        "tp_vs_unsharded": round(base_s / tp_s, 2),
        "tp": args.tp, "kv_layout": args.kv_layout,
        "requests": args.requests, "gen": gen, "slots": args.slots,
        "model": f"{args.layers}Lx{args.hidden}d",
        "family": args.family, "max_len": max_len,
        "params_sharded": shard_ok, "cache_sharded": cache_sharded,
        "recompiles_after_warmup": [
            traces_after[0] - traces_warm[0],
            traces_after[1] - traces_warm[1]],
        "stream_mismatches": mismatches,
    }), flush=True)
    ok = (mismatches == 0 and shard_ok
          and traces_after == traces_warm)
    return 0 if ok else 1


def telemetry_main(args):
    """--telemetry-overhead: the same workload through an engine with
    in-tick telemetry OFF (the PR-4..9 tick shape) and ON (the
    TICK_FIELDS row riding the token pull + the host-side record ring
    + a live JSONL stream). Timed passes ALTERNATE between the two
    warm engines and each side reports its best — the PR-5 paired
    best-of-N methodology (host noise exceeds the effect). One JSON
    line — the BASELINE.md "Serving observability" row."""
    from paddle_tpu.models.decode import next_pow2
    from paddle_tpu.inference.serving import ServingEngine

    gen = args.gen
    max_len = args.max_len or next_pow2(args.prompt_hi + gen)
    params, cfg = _build_family(args, max_len)
    prompts = build_workload(args.requests, args.prompt_lo,
                             args.prompt_hi, args.vocab)
    total = args.requests * gen
    tele_path = os.environ.get("PADDLE_TPU_TELEMETRY_JSONL") or \
        os.path.join(tempfile.mkdtemp(prefix="bench_tele_"),
                     "serve.jsonl")
    _log(f"telemetry A/B: {args.requests} reqs, gen {gen}, "
         f"{args.family} {args.layers}Lx{args.hidden}d -> {tele_path}")

    def build(**kw):
        eng = ServingEngine(params, cfg, family=args.family,
                            num_slots=args.slots, max_len=max_len, **kw)
        warm = eng.generate(prompts, gen)         # compile everything
        return eng, warm

    eng_off, warm_off = build(telemetry="off")
    eng_on, warm_on = build(telemetry="on", telemetry_jsonl=tele_path)
    mismatch = sum(1 for a, b in zip(warm_off, warm_on)
                   if not np.array_equal(a, b))
    best_off = best_on = 1e18
    repeats = 3
    for _ in range(repeats):
        t0 = time.perf_counter()
        outs = eng_off.generate(prompts, gen)
        best_off = min(best_off, time.perf_counter() - t0)
        mismatch += sum(1 for a, b in zip(warm_off, outs)
                        if not np.array_equal(a, b))
        t0 = time.perf_counter()
        outs = eng_on.generate(prompts, gen)
        best_on = min(best_on, time.perf_counter() - t0)
        mismatch += sum(1 for a, b in zip(warm_off, outs)
                        if not np.array_equal(a, b))
    eng_on.flush_telemetry()
    eng_on.export_slo_jsonl(tele_path)
    ticks = [r for r in eng_on.tick_records()
             if r["kind"] == "serving_tick"]
    tps_off, tps_on = total / best_off, total / best_on
    overhead = (tps_off - tps_on) / tps_off * 100.0
    try:
        from telemetry_report import summarize
        parseable = bool(summarize(tele_path).get("serving_ticks"))
    except Exception:
        parseable = False
    print(json.dumps({
        "metric": "serving_telemetry_overhead",
        "value": round(overhead, 2),
        "unit": "%",
        "backend": jax.devices()[0].platform,
        "tokens_per_sec_telemetry_off": round(tps_off, 1),
        "tokens_per_sec_telemetry_on": round(tps_on, 1),
        "requests": args.requests, "gen": gen, "slots": args.slots,
        "repeats": repeats,
        "model": f"{args.layers}Lx{args.hidden}d",
        "family": args.family,
        "decode_traces": [eng_off.trace_counts()[0],
                          eng_on.trace_counts()[0]],
        "tick_records": len(ticks),
        "jsonl_parseable": parseable,
        "stream_mismatches": mismatch,
    }), flush=True)
    return 0 if mismatch == 0 and parseable else 1


def autoscale_main(args):
    """--autoscale-overhead: the same router workload with the
    Autoscaler's control loop OFF vs ON (inference/autoscale.py —
    ticked once per router step, bounds pinned min==max so the loop
    PRICES its steady state: occupancy + burn arithmetic every tick,
    zero scale actions). Timed passes ALTERNATE between the two warm
    fleets and each side reports its best (the PR-5 paired best-of-N
    methodology). One JSON line — the BASELINE.md "Serving control
    loop" row; the acceptance bar is < 5% overhead."""
    from paddle_tpu.models.decode import next_pow2
    from paddle_tpu.inference.router import create_router
    from paddle_tpu.inference.autoscale import (AutoscaleConfig,
                                                Autoscaler)

    gen = args.gen
    max_len = args.max_len or next_pow2(args.prompt_hi + gen)
    params, cfg = _build_family(args, max_len)
    prompts = build_workload(args.requests, args.prompt_lo,
                             args.prompt_hi, args.vocab)
    total = args.requests * gen
    replicas = 2
    _log(f"autoscale A/B: {args.requests} reqs, gen {gen}, "
         f"{args.family} {args.layers}Lx{args.hidden}d, "
         f"{replicas} replicas x {args.slots} slots")

    def build(with_scaler):
        # concurrent=False: both sides run the same single-threaded
        # step loop, so the A/B isolates the scaler arithmetic
        router = create_router(params, cfg, replicas=replicas,
                               family=args.family, num_slots=args.slots,
                               max_len=max_len, concurrent=False)
        scaler = None
        if with_scaler:
            scaler = Autoscaler(
                router, spawn=lambda: (_ for _ in ()).throw(
                    AssertionError("steady-state bench must not spawn")),
                cfg=AutoscaleConfig(min_replicas=replicas,
                                    max_replicas=replicas))
        return router, scaler

    def run(router, scaler):
        reqs = [router.submit(p, gen) for p in prompts]
        while router.has_work():
            router.step()
            if scaler is not None:
                scaler.tick()
        return [np.asarray(r.tokens, np.int32) for r in reqs]

    r_off, _none = build(False)
    r_on, scaler = build(True)
    warm_off = run(r_off, None)                  # compile everything
    warm_on = run(r_on, scaler)
    mismatch = sum(1 for a, b in zip(warm_off, warm_on)
                   if not np.array_equal(a, b))
    best_off = best_on = 1e18
    repeats = 3
    for _ in range(repeats):
        t0 = time.perf_counter()
        outs = run(r_off, None)
        best_off = min(best_off, time.perf_counter() - t0)
        mismatch += sum(1 for a, b in zip(warm_off, outs)
                        if not np.array_equal(a, b))
        t0 = time.perf_counter()
        outs = run(r_on, scaler)
        best_on = min(best_on, time.perf_counter() - t0)
        mismatch += sum(1 for a, b in zip(warm_off, outs)
                        if not np.array_equal(a, b))
    tps_off, tps_on = total / best_off, total / best_on
    overhead = (tps_off - tps_on) / tps_off * 100.0
    st = r_on.stats()
    print(json.dumps({
        "metric": "serving_autoscale_overhead",
        "value": round(overhead, 2),
        "unit": "%",
        "backend": jax.devices()[0].platform,
        "tokens_per_sec_autoscale_off": round(tps_off, 1),
        "tokens_per_sec_autoscale_on": round(tps_on, 1),
        "requests": args.requests, "gen": gen, "slots": args.slots,
        "replicas": replicas, "repeats": repeats,
        "model": f"{args.layers}Lx{args.hidden}d",
        "family": args.family,
        "replicas_live": st["replicas_live"],
        "scale_actions": 0,          # min==max pins the fleet by design
        "stream_mismatches": mismatch,
    }), flush=True)
    return 0 if mismatch == 0 else 1


def admission_main(args):
    """--admission-overhead: the same router workload with the
    overload-resilience machinery OFF vs ON (inference/admission.py +
    journal.py — an AdmissionController with an unmetered default
    tenant, so every submit runs the charge/order/note_dispatch
    arithmetic and every accept/terminal hits the fsynced request WAL,
    but no request is ever rejected, preempted or reordered: the A/B
    prices the steady state, not the policies). Timed passes ALTERNATE
    between the two warm fleets and each side reports its best (the
    PR-5 paired methodology). One JSON line — the BASELINE.md
    "Overload resilience" row; the acceptance bar is < 5% overhead and
    ZERO stream mismatches (admission must not perturb greedy
    streams)."""
    import tempfile
    from paddle_tpu.models.decode import next_pow2
    from paddle_tpu.inference.router import create_router
    from paddle_tpu.profiler import monitor

    gen = args.gen
    max_len = args.max_len or next_pow2(args.prompt_hi + gen)
    params, cfg = _build_family(args, max_len)
    prompts = build_workload(args.requests, args.prompt_lo,
                             args.prompt_hi, args.vocab)
    total = args.requests * gen
    replicas = 2
    _log(f"admission A/B: {args.requests} reqs, gen {gen}, "
         f"{args.family} {args.layers}Lx{args.hidden}d, "
         f"{replicas} replicas x {args.slots} slots")
    jdir = tempfile.mkdtemp(prefix="bench_admission_wal_")

    def build(with_admission):
        # concurrent=False: both sides run the same single-threaded
        # step loop, so the A/B isolates admission + WAL arithmetic
        kw = {}
        if with_admission:
            kw = {"admission": {}, "journal_dir": jdir}
        return create_router(params, cfg, replicas=replicas,
                             family=args.family, num_slots=args.slots,
                             max_len=max_len, concurrent=False, **kw)

    def run(router):
        reqs = [router.submit(p, gen) for p in prompts]
        router.drain()
        return [np.asarray(r.tokens, np.int32) for r in reqs]

    r_off = build(False)
    r_on = build(True)
    warm_off = run(r_off)                        # compile everything
    warm_on = run(r_on)
    mismatch = sum(1 for a, b in zip(warm_off, warm_on)
                   if not np.array_equal(a, b))
    best_off = best_on = 1e18
    repeats = 3
    for _ in range(repeats):
        t0 = time.perf_counter()
        outs = run(r_off)
        best_off = min(best_off, time.perf_counter() - t0)
        mismatch += sum(1 for a, b in zip(warm_off, outs)
                        if not np.array_equal(a, b))
        t0 = time.perf_counter()
        outs = run(r_on)
        best_on = min(best_on, time.perf_counter() - t0)
        mismatch += sum(1 for a, b in zip(warm_off, outs)
                        if not np.array_equal(a, b))
    tps_off, tps_on = total / best_off, total / best_on
    overhead = (tps_off - tps_on) / tps_off * 100.0
    st = r_on.stats()
    r_on.close()
    print(json.dumps({
        "metric": "serving_admission_overhead",
        "value": round(overhead, 2),
        "unit": "%",
        "backend": jax.devices()[0].platform,
        "tokens_per_sec_admission_off": round(tps_off, 1),
        "tokens_per_sec_admission_on": round(tps_on, 1),
        "requests": args.requests, "gen": gen, "slots": args.slots,
        "replicas": replicas, "repeats": repeats,
        "model": f"{args.layers}Lx{args.hidden}d",
        "family": args.family,
        "journal_appends": monitor.counter(
            "serving.journal.appends").value,
        "journal_replayable": st["journal"]["replayable"],
        "rejections": 0,             # unmetered default by design
        "stream_mismatches": mismatch,
    }), flush=True)
    return 0 if mismatch == 0 else 1


def router_main(args):
    """--router R: aggregate tokens/s through the replicated-engine
    router (inference/router.py) vs ONE engine at the same per-replica
    shape, on a workload deep enough that concurrency is the limit
    (requests >> one replica's slots). Near-linear scaling at R=2 on
    the CPU rung is the acceptance bar: the tick cost is dispatch-
    dominated at bench scale, so R replicas serve R x the streams in
    the same number of tick rounds. One JSON line — the BASELINE.md
    "Sharded serving" router row."""
    from paddle_tpu.models.decode import next_pow2
    from paddle_tpu.inference.serving import ServingEngine
    from paddle_tpu.inference.router import create_router
    from paddle_tpu.profiler import monitor

    gen = args.gen
    max_len = args.max_len or next_pow2(args.prompt_hi + gen)
    params, cfg = _build_family(args, max_len)
    # concurrency-limited workload unless the operator sized it: 4
    # waves for the single engine, 4/R waves behind the router (an
    # EXPLICIT --requests always wins — the flag defaults to None so
    # "--requests 16" is 16, not this auto-sizing)
    n_req = (args.requests if args.requests is not None
             else 4 * args.slots)
    prompts = build_workload(n_req, args.prompt_lo, args.prompt_hi,
                             args.vocab)
    total_tokens = n_req * gen
    _log(f"router workload: {n_req} reqs, gen {gen}, {args.family} "
         f"{args.layers}Lx{args.hidden}d, {args.router} replicas x "
         f"{args.slots} slots")

    single = ServingEngine(params, cfg, family=args.family,
                           num_slots=args.slots, max_len=max_len)
    single.generate(prompts, gen)                # warm
    t0 = time.perf_counter()
    base_outs = single.generate(prompts, gen)
    base_s = time.perf_counter() - t0

    tele_path = os.environ.get("PADDLE_TPU_TELEMETRY_JSONL")
    router = create_router(params, cfg, replicas=args.router,
                           family=args.family, num_slots=args.slots,
                           max_len=max_len,
                           telemetry_jsonl=tele_path)  # fans out .r<i>
    router.generate(prompts, gen)                # warm
    # snapshot the (process-global) dispatch counters so the reported
    # balance covers the MEASURED pass only, not the warm run
    disp0 = [r["dispatched"] for r in router.stats()["per_replica"]]
    t0 = time.perf_counter()
    outs = router.generate(prompts, gen)
    rt_s = time.perf_counter() - t0

    mismatches = sum(1 for a, b in zip(base_outs, outs)
                     if not np.array_equal(a, b))
    st = router.stats()
    disp = [r["dispatched"] - d0
            for r, d0 in zip(st["per_replica"], disp0)]
    scaling = base_s / rt_s
    fleet = None
    if tele_path:
        monitor.registry().export_jsonl(tele_path)
        # per-replica serving JSONLs (tick stream + SLO samples) ->
        # the fleet aggregate report (telemetry_report --fleet)
        paths = []
        for i, rep in enumerate(router.replicas):
            p = f"{tele_path}.r{i}"
            rep.eng.flush_telemetry()
            rep.eng.export_slo_jsonl(p)
            paths.append(p)
        try:
            from telemetry_report import summarize_fleet
            fleet = summarize_fleet(paths)
            _log("fleet: " + json.dumps(
                {k: fleet[k] for k in ("balance", "fleet", "burn_rate")
                 if k in fleet}))
        except Exception as e:
            _log(f"fleet report failed: {e}")
    print(json.dumps({
        "metric": "serving_router_tokens_per_sec",
        "value": round(total_tokens / rt_s, 1),
        "unit": f"aggregate tokens/s @ {args.router} replicas",
        "backend": jax.devices()[0].platform,
        "single_engine_tokens_per_sec": round(total_tokens / base_s, 1),
        "scaling_vs_single": round(scaling, 2),
        "replicas": args.router,
        "requests": n_req, "gen": gen, "slots": args.slots,
        "model": f"{args.layers}Lx{args.hidden}d",
        "family": args.family, "max_len": max_len,
        "dispatched_per_replica": disp,
        "replicas_live": st["replicas_live"],
        "stream_mismatches": mismatches,
        "fleet_balance": None if fleet is None else fleet.get("balance"),
    }), flush=True)
    return 0 if mismatches == 0 else 1


def multi_tick_main(args):
    """--multi-tick K: fused multi-tick decode A/B (BASELINE.md
    "Disaggregated serving") — single-tick engine vs multi_tick=K
    engine on single-stream AND concurrent workloads, bit-parity
    checked. The single-stream leg is the dispatch-amortization
    observable: one jitted lax.scan runs K decode ticks per dispatch,
    so the host pays one dispatch + one pull per K tokens
    (serving.decode_ticks counts DISPATCHES — the tokens/dispatch
    ratio printed here is the one-pull-per-K-tokens assertion). One
    JSON line; --adopt writes the evidence-gated registry row
    (kernels/registry.py "multi_tick": parity + >=1.5x single-stream
    + zero recompiles)."""
    from paddle_tpu.models.decode import next_pow2
    from paddle_tpu.inference.serving import ServingEngine
    from paddle_tpu.profiler import monitor

    gen = args.gen
    K = args.multi_tick
    max_len = args.max_len or next_pow2(args.prompt_hi + gen + K)
    params, cfg = _build_family(args, max_len)
    prompts = build_workload(args.requests, args.prompt_lo,
                             args.prompt_hi, args.vocab)
    total_tokens = args.requests * gen
    _log(f"multi-tick workload: {args.requests} single streams x {gen} "
         f"tok, {args.family} {args.layers}Lx{args.hidden}d, K={K}, "
         f"max_len={max_len}")

    def run(eng):
        t0 = time.perf_counter()
        outs = eng.generate(prompts, gen)
        return time.perf_counter() - t0, outs

    def ticks():
        return monitor.counter("serving.decode_ticks").value

    def timed(eng, reps=3):
        # best-of-reps: the CPU rung's host-load swings (BASELINE.md
        # "CPU bench rung noise") dwarf the short timed window, and the
        # best rep is the least-perturbed one. Dispatch counts are
        # deterministic — every rep's delta is identical.
        best_s, outs, tick_delta = math.inf, None, 0
        for _ in range(reps):
            k0 = ticks()
            s, outs = run(eng)
            tick_delta = ticks() - k0
            best_s = min(best_s, s)
        return best_s, outs, tick_delta

    base = ServingEngine(params, cfg, family=args.family, num_slots=1,
                         max_len=max_len)
    run(base)                                        # warm
    base_s, base_outs, base_ticks = timed(base)

    mt = ServingEngine(params, cfg, family=args.family, num_slots=1,
                       max_len=max_len, multi_tick=K)
    run(mt)                                          # warm
    traces_warm = mt.trace_counts()
    mt_s, mt_outs, mt_ticks = timed(mt)
    traces_after = mt.trace_counts()

    mismatches = sum(1 for a, b in zip(base_outs, mt_outs)
                     if not np.array_equal(a, b))
    base_tps = total_tokens / base_s
    mt_tps = total_tokens / mt_s
    # one dispatch (== one host pull) per K tokens: each stream of
    # `gen` tokens needs ceil(gen/K) dispatches
    expected_dispatches = args.requests * -(-gen // K)
    tokens_per_dispatch = total_tokens / max(mt_ticks, 1)

    # concurrent leg: same engines' shape at --slots concurrency — the
    # ITL p99 check (per-token latency is the amortized share of each
    # K-token pull, so p99 must not blow up under batching)
    conc = ServingEngine(params, cfg, family=args.family,
                         num_slots=args.slots, max_len=max_len,
                         multi_tick=K)
    conc.generate(prompts, gen)                      # warm
    conc.slo_snapshot()["itl_ms"]                    # (ring persists)
    conc._slo_itl.clear()
    t0 = time.perf_counter()
    conc_outs = conc.generate(prompts, gen)
    conc_s = time.perf_counter() - t0
    itl = sorted(conc.slo_snapshot()["itl_ms"])
    itl_p99 = itl[int(0.99 * (len(itl) - 1))] if itl else None
    mismatches += sum(1 for a, b in zip(base_outs, conc_outs)
                      if not np.array_equal(a, b))

    doc = {
        "metric": "serving_multi_tick_tokens_per_sec",
        "value": round(mt_tps, 1),
        "unit": "single-stream tokens/s",
        "backend": jax.devices()[0].platform,
        "single_tick_tokens_per_sec": round(base_tps, 1),
        "speedup_vs_single_tick": round(mt_tps / base_tps, 2),
        "ticks_per_dispatch": K,
        "tokens_per_dispatch_measured": round(tokens_per_dispatch, 2),
        "dispatches": [base_ticks, mt_ticks],
        "dispatches_expected": expected_dispatches,
        "concurrent_tokens_per_sec": round(total_tokens / conc_s, 1),
        "concurrent_itl_p99_ms": (None if itl_p99 is None
                                  else round(itl_p99, 3)),
        "requests": args.requests, "gen": gen, "slots": args.slots,
        "model": f"{args.layers}Lx{args.hidden}d",
        "family": args.family, "max_len": max_len,
        "recompiles_after_warmup": [
            traces_after[0] - traces_warm[0],
            traces_after[1] - traces_warm[1]],
        "stream_mismatches": mismatches,
    }
    if args.adopt:
        from paddle_tpu.kernels import registry
        ok = (mismatches == 0
              and doc["speedup_vs_single_tick"] >= 1.5
              and doc["recompiles_after_warmup"] == [0, 0]
              and mt_ticks <= expected_dispatches)
        if not ok:
            doc["adopt"] = "refused: speedup/parity/recompile gate failed"
        else:
            pbytes = sum(np.asarray(v).nbytes for v in params.values())
            per_dispatch_ms = mt_s * 1e3 / max(mt_ticks, 1)
            problem = registry.adopt(
                "multi_tick", "scan", per_dispatch_ms,
                bytes_moved=pbytes * K,
                source=(f"bench_serving --multi-tick {K}: "
                        f"{doc['speedup_vs_single_tick']}x single-stream "
                        f"vs single-tick ({tokens_per_dispatch:.1f} "
                        f"tok/dispatch measured, K={K}; dispatch-bound "
                        "rungs only — at step-sized device work the scan "
                        "amortizes nothing)"))
            doc["adopt"] = problem or "adopted"
    print(json.dumps(doc), flush=True)
    return 0 if mismatches == 0 else 1


def role_split_main(args):
    """--role-split: prefill/decode disaggregation A/B (the isolation
    acceptance). Two 2-replica fleets serve the SAME trace: a few
    long-lived decode streams (the victims) plus a flood of
    long-prompt short-gen requests arriving mid-decode. The
    homogeneous fleet interleaves flood prefills with the victims'
    ticks on the same engines; the role-split fleet admits the flood
    on the prefill replica only and hands streams to the decode
    replica at first token — victim ITL p99 must stay flat while
    serving.prefills stays == requests (zero re-prefilled tokens
    across every handoff). ITL is measured over STEADY-STATE decode
    (each victim's tokens 8+): the one-time admission/handoff
    transient is priced by the handoff counter, not smeared into the
    isolation percentile. One JSON line."""
    from paddle_tpu.models.decode import next_pow2
    from paddle_tpu.inference.router import create_router
    from paddle_tpu.profiler import monitor

    gen = args.gen
    max_len = args.max_len or next_pow2(args.prompt_hi + gen)
    params, cfg = _build_family(args, max_len)
    rng = np.random.RandomState(7)
    victims = [rng.randint(1, args.vocab - 1, size=args.prompt_lo)
               .astype(np.int32) for _ in range(2)]
    flood = [rng.randint(1, args.vocab - 1, size=args.prompt_hi)
             .astype(np.int32) for _ in range(args.requests)]
    _log(f"role-split workload: 2 victims x {gen} tok + "
         f"{args.requests}-request prefill flood "
         f"(prompts {args.prompt_hi} tok, gen 2)")

    def run(roles):
        router = create_router(params, cfg, replicas=2,
                               family=args.family, num_slots=args.slots,
                               max_len=max_len, roles=roles)
        # warm every executable (prefill buckets + decode) on both
        # replicas before the measured trace
        router.generate(victims + flood[:2], 4)
        pre0 = monitor.counter("serving.prefills").value
        vreqs = [router.submit(p, gen) for p in victims]
        gaps = {id(r): [] for r in vreqs}
        last = {id(r): None for r in vreqs}
        seen = {id(r): 0 for r in vreqs}
        flooded = 0
        t0 = time.perf_counter()
        while router.has_work() or flooded < len(flood):
            # flood arrives paced across the victims' WHOLE decode
            # (one prefill every other tick), not as one front-loaded
            # burst — the homogeneous fleet must keep interleaving
            # prefills with victim ticks for the isolation A/B to
            # measure anything
            while (flooded < len(flood)
                   and 2 * flooded <= router._ticks):
                router.submit(flood[flooded], 2)
                flooded += 1
            now = time.perf_counter()
            for r, tok in router.step():
                if id(r) in gaps:
                    seen[id(r)] += 1
                    # steady state only: tokens 8+ (past the
                    # admission/handoff transient)
                    if last[id(r)] is not None and seen[id(r)] > 8:
                        gaps[id(r)].append((now - last[id(r)]) * 1e3)
                    last[id(r)] = now
        wall = time.perf_counter() - t0
        itl = sorted(g for gs in gaps.values() for g in gs)
        p99 = itl[int(0.99 * (len(itl) - 1))] if itl else None
        p50 = itl[len(itl) // 2] if itl else None
        st = router.stats()
        prefills = monitor.counter("serving.prefills").value - pre0
        return {"itl_p99_ms": None if p99 is None else round(p99, 3),
                "itl_p50_ms": None if p50 is None else round(p50, 3),
                "wall_s": round(wall, 3),
                "victim_tokens": [len(r.tokens) for r in vreqs],
                "victims_done": all(r.done for r in vreqs),
                "prefills": prefills,
                "handoffs": st["handoffs"]}

    hand0 = monitor.counter("serving.router.handoffs").value
    baseline = run(None)
    split = run(["prefill", "decode"])
    split["handoffs"] -= hand0 + baseline["handoffs"]
    # zero re-prefill: one completed prefill per submitted request
    # (2 victims + the flood), handoffs notwithstanding
    n_req = 2 + len(flood)
    ok = (split["victims_done"] and baseline["victims_done"]
          and split["prefills"] == n_req)
    doc = {
        "metric": "serving_role_split_itl_p99_ms",
        "value": split["itl_p99_ms"],
        "unit": "victim decode ITL p99 (ms) under prefill flood",
        "backend": jax.devices()[0].platform,
        "homogeneous": baseline, "role_split": split,
        "p99_ratio_vs_homogeneous": (
            None if not baseline["itl_p99_ms"] or not split["itl_p99_ms"]
            else round(split["itl_p99_ms"] / baseline["itl_p99_ms"], 2)),
        "flood_requests": len(flood), "gen": gen, "slots": args.slots,
        "model": f"{args.layers}Lx{args.hidden}d",
        "family": args.family, "max_len": max_len,
        "zero_reprefill": split["prefills"] == n_req,
    }
    print(json.dumps(doc), flush=True)
    return 0 if ok else 1


def main():
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--requests", type=int, default=None,
                    help="workload size (default 16; --router defaults "
                         "to 4*slots unless set explicitly)")
    ap.add_argument("--gen", type=int, default=32)
    ap.add_argument("--slots", type=int, default=8)
    ap.add_argument("--prompt-lo", type=int, default=8)
    ap.add_argument("--prompt-hi", type=int, default=96)
    ap.add_argument("--family", choices=("gpt", "llama"), default="gpt")
    ap.add_argument("--layers", type=int, default=2)
    ap.add_argument("--hidden", type=int, default=128)
    ap.add_argument("--vocab", type=int, default=512)
    ap.add_argument("--max-len", type=int, default=0,
                    help="cache length (0 = next pow2 of hi+gen)")
    ap.add_argument("--tpu", action="store_true",
                    help="run on the default (TPU) backend")
    ap.add_argument("--capacity", action="store_true",
                    help="paged-vs-dense capacity bench at equal KV HBM")
    ap.add_argument("--chunk-slo", action="store_true",
                    help="inter-token p99 while a max-length prompt "
                         "prefills: monolithic vs chunked")
    ap.add_argument("--spec", action="store_true",
                    help="single-stream speculative-decode A/B "
                         "(non-spec vs spec engine, bit-parity checked)")
    ap.add_argument("--gamma", type=int, default=4,
                    help="--spec: draft length per tick")
    ap.add_argument("--draft-layers", type=int, default=0,
                    help="--spec: self-draft depth (0 = full stack, "
                         "the acceptance ceiling on random-init params)")
    ap.add_argument("--sweep", action="store_true",
                    help="--spec: acceptance vs gamma/draft-depth table")
    ap.add_argument("--adopt", action="store_true",
                    help="--spec/--quant: write the evidence-gated "
                         "registry row (spec: speedup >= 1.5x; quant: "
                         "weight bytes <= 0.55x AND tokens/s >= 0.95x)")
    ap.add_argument("--quant", action="store_true",
                    help="weight-only int8 A/B: fp vs quant engine, "
                         "weight bytes + tokens/s + logit error budget")
    ap.add_argument("--tp", type=int, default=0,
                    help="tensor-parallel decode on an N-way CPU mesh "
                         "vs unsharded (bit-parity + mechanics)")
    ap.add_argument("--router", type=int, default=0,
                    help="aggregate tokens/s through N replicated "
                         "engines (inference/router.py) vs one engine")
    ap.add_argument("--multi-tick", type=int, default=0,
                    help="fused multi-tick decode A/B: single-tick vs "
                         "multi_tick=K engine (one dispatch + one pull "
                         "per K tokens; bit-parity checked)")
    ap.add_argument("--role-split", action="store_true",
                    help="prefill/decode disaggregation A/B: victim "
                         "decode ITL p99 under a prefill flood, "
                         "homogeneous vs role-split 2-replica fleet")
    ap.add_argument("--kv-layout", choices=("auto", "dense", "paged"),
                    default="auto", help="--tp: cache layout under test")
    ap.add_argument("--telemetry-overhead", action="store_true",
                    help="A/B in-tick telemetry off vs on (paired "
                         "best-of-3, bit-parity checked)")
    ap.add_argument("--autoscale-overhead", action="store_true",
                    help="A/B the Autoscaler control loop off vs on "
                         "over a 2-replica router (steady state, "
                         "paired best-of-3, bit-parity checked)")
    ap.add_argument("--admission-overhead", action="store_true",
                    help="A/B multi-tenant admission + request WAL "
                         "off vs on over a 2-replica router (steady "
                         "state, paired best-of-3, bit-parity checked)")
    args = ap.parse_args()
    if args.tp and args.tp != _TP:
        ap.error("--tp was read pre-init for the CPU pin; don't "
                 "rewrite sys.argv between import and main()")
    if args.tp:
        if args.requests is None:
            args.requests = 16
        return tp_main(args)
    if args.router:
        return router_main(args)          # sizes its own default
    if args.requests is None:
        args.requests = 16
    if args.multi_tick:
        return multi_tick_main(args)
    if args.role_split:
        return role_split_main(args)
    if args.telemetry_overhead:
        return telemetry_main(args)
    if args.autoscale_overhead:
        return autoscale_main(args)
    if args.admission_overhead:
        return admission_main(args)
    if args.capacity:
        return capacity_main(args)
    if args.chunk_slo:
        return chunk_slo_main(args)
    if args.spec:
        return spec_main(args)
    if args.quant:
        return quant_main(args)

    from paddle_tpu.models.decode import next_pow2
    from paddle_tpu.inference.serving import ServingEngine
    from paddle_tpu.profiler import monitor

    max_len = args.max_len or next_pow2(args.prompt_hi + args.gen)
    if args.family == "gpt":
        from paddle_tpu.models.gpt import (GPTConfig, init_gpt_params,
                                           greedy_generate)
        cfg = GPTConfig(vocab_size=args.vocab, hidden_size=args.hidden,
                        num_layers=args.layers,
                        num_heads=max(args.hidden // 32, 1),
                        max_seq_len=2 * max_len, sequence_parallel=False,
                        remat=False, dtype=jnp.float32)
        params = init_gpt_params(cfg, jax.random.PRNGKey(0))
    else:
        from paddle_tpu.models.llama import (LlamaConfig,
                                             init_llama_params,
                                             greedy_generate)
        cfg = LlamaConfig(vocab_size=args.vocab, hidden_size=args.hidden,
                          num_layers=args.layers,
                          num_heads=max(args.hidden // 32, 1),
                          num_kv_heads=max(args.hidden // 64, 1),
                          max_seq_len=2 * max_len, remat=False,
                          dtype=jnp.float32)
        params = init_llama_params(cfg, jax.random.PRNGKey(0))

    prompts = build_workload(args.requests, args.prompt_lo,
                             args.prompt_hi, args.vocab)
    total_tokens = args.requests * args.gen
    _log(f"workload: {args.requests} reqs, prompts "
         f"{args.prompt_lo}-{args.prompt_hi}, gen {args.gen}, "
         f"{args.family} {args.layers}Lx{args.hidden}d, "
         f"slots={args.slots}, max_len={max_len}")

    # ---- sequential per-request baseline (warm pass then timed pass)
    seq_s, seq_outs = run_sequential(params, cfg, prompts, args.gen,
                                     max_len, greedy_generate)
    seq_tps = total_tokens / seq_s
    _log(f"sequential: {seq_s * 1e3:.1f} ms total ({seq_tps:.1f} tok/s)")

    # ---- continuous batching: warm pass, then timed on warm traces
    tele_path = os.environ.get("PADDLE_TPU_TELEMETRY_JSONL")
    eng = ServingEngine(params, cfg, family=args.family,
                        num_slots=args.slots, max_len=max_len)
    eng.generate(prompts, args.gen)
    traces_warm = eng.trace_counts()
    if tele_path:
        monitor.registry().export_jsonl(tele_path)
    t0 = time.perf_counter()
    outs = eng.generate(prompts, args.gen)
    eng_s = time.perf_counter() - t0
    traces_after = eng.trace_counts()
    if tele_path:
        monitor.registry().export_jsonl(tele_path)
        eng.export_slo_jsonl(tele_path)    # TTFT / inter-token samples
        try:
            from telemetry_report import summarize
            _log("telemetry: " + json.dumps(
                summarize(tele_path).get("serving", {})))
        except Exception as e:
            _log(f"telemetry report failed: {e}")
    eng_tps = total_tokens / eng_s
    _log(f"engine: {eng_s * 1e3:.1f} ms total ({eng_tps:.1f} tok/s)")

    # correctness on the way out: greedy engine streams must equal the
    # per-request sequential ones token for token
    mismatches = sum(1 for a, b in zip(seq_outs, outs)
                     if not np.array_equal(a, b))
    recompiles = (traces_after[0] - traces_warm[0],
                  traces_after[1] - traces_warm[1])
    srv = {k[len("serving."):]: v for k, v in monitor.snapshot().items()
           if k.startswith("serving.")}
    try:   # compiled peak HBM of the decode tick rides the BENCH line
        peak_hbm = eng.compiled_memory_stats().get("peak_bytes")
    except Exception as e:            # backend may not report memory
        _log(f"compiled memory stats unavailable: {e}")
        peak_hbm = None
    print(json.dumps({
        "metric": "serving_tokens_per_sec",
        "value": round(eng_tps, 1),
        "unit": "tokens/s",
        "backend": jax.devices()[0].platform,
        "sequential_tokens_per_sec": round(seq_tps, 1),
        "speedup_vs_sequential": round(eng_tps / seq_tps, 2),
        "requests": args.requests, "gen": args.gen,
        "slots": args.slots, "family": args.family,
        "prompt_range": [args.prompt_lo, args.prompt_hi],
        "model": f"{args.layers}Lx{args.hidden}d",
        "recompiles_after_warmup": list(recompiles),
        "stream_mismatches": mismatches,
        "compiled_peak_hbm_bytes": peak_hbm,
        "monitor": srv,
    }), flush=True)
    return 0 if mismatches == 0 else 1


if __name__ == "__main__":
    sys.exit(main())
