"""Measure the BASELINE.md ladder rows beyond the headline GPT bench:
MNIST-MLP steps/sec, BERT-base-ish jit tokens/sec, ResNet-50 images/sec.

Each row runs in a subprocess under a timeout (tunnel resilience, like
bench.py) and prints one JSON line; run on the TPU-attached host:
    python tools/bench_ladder.py            # all rows
    python tools/bench_ladder.py --run mnist
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
import time

ROWS = ["mnist", "bert", "resnet50", "ernie_vil"]


def _bench_loop(step, iters=10):
    t0 = time.perf_counter()
    out = step()
    _force(out)
    compile_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    for _ in range(iters):
        out = step()
    _force(out)
    return compile_s, (time.perf_counter() - t0) / iters


def _force(out):
    import jax
    for leaf in jax.tree_util.tree_leaves(out):
        if hasattr(leaf, "block_until_ready"):
            float(leaf.ravel()[0])
            break


def run_row(row: str) -> None:
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    # shared with bench.py so the two measurement paths can't drift
    # (applies BEFORE any jax trace so env gates read the right values);
    # --run is also how tpu_campaign invokes single rows
    from bench import apply_perf_env_defaults, sync_compile_cache_for
    apply_perf_env_defaults()
    import jax
    import jax.numpy as jnp
    import functools
    import numpy as np
    devs = jax.devices()
    platform = devs[0].platform
    # TPU-only compile cache: undo the env-inherited dir on CPU runs
    sync_compile_cache_for(platform)

    if row == "mnist":
        # BASELINE config 1: MNIST MLP train step (784-512-512-10)
        import paddle_tpu as paddle
        import paddle_tpu.nn as nn
        paddle.seed(0)
        net = nn.Sequential(nn.Linear(784, 512), nn.ReLU(),
                            nn.Linear(512, 512), nn.ReLU(),
                            nn.Linear(512, 10))
        opt = paddle.optimizer.Adam(learning_rate=1e-3,
                                    parameters=net.parameters())
        loss_fn = nn.CrossEntropyLoss()
        x = paddle.to_tensor(np.random.RandomState(0)
                             .randn(256, 784).astype(np.float32))
        y = paddle.to_tensor(np.random.RandomState(1)
                             .randint(0, 10, 256).astype(np.int64))

        def step():
            loss = loss_fn(net(x), y)
            loss.backward()
            opt.step()
            opt.clear_grad()
            return loss._value
        compile_s, dt = _bench_loop(step, iters=20)
        print(json.dumps({"row": "mnist_mlp", "metric": "steps_per_sec",
                          "value": round(1.0 / dt, 2),
                          "batch": 256, "compile_s": round(compile_s, 1),
                          "platform": platform}), flush=True)

    elif row == "bert":
        # BASELINE config 2: BERT-base MLM train step (the real encoder,
        # models/bert.py) via one jitted graph
        import optax
        from paddle_tpu.models.bert import (BertConfig, init_bert_params,
                                            bert_mlm_loss)
        cfg = BertConfig(vocab_size=30522, hidden_size=768, num_layers=12,
                        num_heads=12, max_seq_len=512, dtype=jnp.bfloat16)
        params = init_bert_params(cfg, jax.random.PRNGKey(0))
        opt = optax.adamw(1e-4)
        opt_state = opt.init(params)
        tokens = jax.random.randint(jax.random.PRNGKey(1), (16, 512), 0,
                                    cfg.vocab_size)
        # 15% MLM masking
        labels = jnp.where(
            jax.random.uniform(jax.random.PRNGKey(2), (16, 512)) < 0.15,
            tokens, -100)
        batch = {"tokens": tokens, "labels": labels}

        from paddle_tpu.models.facade import make_train_step

        @make_train_step
        def step(params, opt_state, batch):
            loss, g = jax.value_and_grad(
                functools.partial(bert_mlm_loss, cfg=cfg))(params, batch)
            upd, opt_state = opt.update(g, opt_state, params)
            return loss, optax.apply_updates(params, upd), opt_state

        def run():
            nonlocal params, opt_state
            loss, params, opt_state = step(params, opt_state, batch)
            return loss
        compile_s, dt = _bench_loop(run, iters=10)
        tps = 16 * 512 / dt
        n_params = sum(int(v.size)
                       for v in jax.tree_util.tree_leaves(params))
        flops_per_tok = 6.0 * n_params + 12.0 * 12 * 768 * 512
        # device-kind-keyed peak table shared with bench.py (repo root is
        # already on sys.path — run_row inserts it first thing)
        from bench import _peak_for
        peak = _peak_for(devs[0].device_kind, platform)
        print(json.dumps({"row": "bert_base_jit",
                          "metric": "tokens_per_sec_per_chip",
                          "value": round(tps, 1),
                          "mfu": round(flops_per_tok * tps / peak, 4),
                          "compile_s": round(compile_s, 1),
                          "platform": platform}), flush=True)

    elif row == "resnet50":
        # BASELINE config 4: ResNet-50 fwd+bwd images/sec (functional core
        # jitted in one graph)
        import paddle_tpu as paddle
        from paddle_tpu.vision.models import resnet50
        paddle.seed(0)
        net = resnet50(num_classes=1000)
        import paddle_tpu.nn as nn
        opt = paddle.optimizer.Momentum(learning_rate=0.1, momentum=0.9,
                                        parameters=net.parameters())
        loss_fn = nn.CrossEntropyLoss()
        B = 64 if platform in ("tpu", "axon") else 4
        x = paddle.to_tensor(np.random.RandomState(0)
                             .randn(B, 3, 224, 224).astype(np.float32))
        y = paddle.to_tensor(np.random.RandomState(1)
                             .randint(0, 1000, B).astype(np.int64))

        # fwd+loss as ONE traced op (to_static): eager per-op dispatch
        # would mean 100+ separate remote compiles over the tunnel; the
        # reference's analog row also runs the conv stack as one graph
        net.train()
        fwd_loss = paddle.jit.to_static(lambda xx, yy: loss_fn(net(xx), yy))

        def step():
            loss = fwd_loss(x, y)
            loss.backward()
            opt.step()
            opt.clear_grad()
            return loss._value
        compile_s, dt = _bench_loop(step, iters=5)
        print(json.dumps({"row": "resnet50", "metric": "images_per_sec",
                          "value": round(B / dt, 1), "batch": B,
                          "compile_s": round(compile_s, 1),
                          "platform": platform}), flush=True)

    elif row == "ernie_vil":
        # BASELINE config 5: ERNIE-ViL dual-encoder contrastive step,
        # samples/sec/chip (ViT-base image tower + BERT-base text tower)
        import optax
        from paddle_tpu.models.ernie_vil import (ErnieViLConfig,
                                                 init_ernie_vil_params,
                                                 contrastive_loss)
        cfg = ErnieViLConfig()
        B = 32 if platform in ("tpu", "axon") else 2
        params = init_ernie_vil_params(cfg, jax.random.PRNGKey(0))
        opt = optax.adamw(1e-4)
        opt_state = opt.init(params)
        batch = {
            "tokens": jax.random.randint(jax.random.PRNGKey(1), (B, 64),
                                         0, cfg.text.vocab_size),
            "images": jax.random.normal(jax.random.PRNGKey(2),
                                        (B, 3, 224, 224), jnp.float32),
        }

        from paddle_tpu.models.facade import make_train_step

        @make_train_step
        def step(params, opt_state, batch):
            loss, g = jax.value_and_grad(functools.partial(
                contrastive_loss, cfg=cfg))(params, batch)
            upd, opt_state = opt.update(g, opt_state, params)
            return loss, optax.apply_updates(params, upd), opt_state

        def run():
            nonlocal params, opt_state
            loss, params, opt_state = step(params, opt_state, batch)
            return loss
        compile_s, dt = _bench_loop(run, iters=5)
        print(json.dumps({"row": "ernie_vil_dual_encoder",
                          "metric": "samples_per_sec_per_chip",
                          "value": round(B / dt, 1), "batch": B,
                          "compile_s": round(compile_s, 1),
                          "platform": platform}), flush=True)


def main():
    here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    for row in ROWS:
        print(f"[ladder] === {row} ===", file=sys.stderr, flush=True)
        try:
            res = subprocess.run(
                [sys.executable, os.path.abspath(__file__), "--run", row],
                cwd=here, stdout=subprocess.PIPE, timeout=1500)
        except subprocess.TimeoutExpired:
            print(f"[ladder] {row}: TIMEOUT", file=sys.stderr, flush=True)
            continue
        out = res.stdout.decode().strip()
        line = next((ln for ln in reversed(out.splitlines())
                     if ln.startswith("{")), None)
        if res.returncode == 0 and line:
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                rec = None
            if rec and rec.get("platform") in ("tpu", "axon"):
                sys.path.insert(0, here)
                from bench import record_window
                record_window(f"ladder_{row}", rec, here)
            print(line, flush=True)
        else:
            print(f"[ladder] {row}: FAILED rc={res.returncode}",
                  file=sys.stderr, flush=True)


if __name__ == "__main__":
    if len(sys.argv) >= 3 and sys.argv[1] == "--run":
        run_row(sys.argv[2])
    else:
        main()
