"""Serving chaos drill: the continuous-batching engine under faults.

The executable acceptance test for the serving SLO guardrails
(docs/serving.md "Robustness") — the serving sibling of
tools/chaos_drill.py, in-process because the engine is a single-host
runtime (no launcher/mesh in the loop). Every scenario drives the REAL
ServingEngine over a mixed-length workload with a declared fault
(paddle_tpu.testing.faults serving kinds) and asserts the three
guardrail invariants:

1. every submitted request ends in EXACTLY ONE terminal finish_reason
   (TERMINAL_REASONS — no request in limbo, ever);
2. surviving streams are BIT-IDENTICAL to the fault-free run, and
   early-terminated streams (poisoned/cancelled/timeout/evicted) are
   exact PREFIXES of it — per-request isolation inside the shared
   batch, the Orca/vLLM correctness requirement;
3. eventful faults leave a parseable flight-recorder dump, and the
   trace-count ceilings hold (decode <= 2; prefill <= 2*log2(max_len))
   — the guardrails cost no recompiles.

Scenarios:
  nan_logits@T:S   in-jit poisoned logit row -> only slot S's request
                   ends "poisoned"; co-batched survivors exact
  tick_stall@T:MS  host pull stalls mid-drill -> watchdog backoff
                   recovers, serving.retries > 0, streams exact
  prefill_raise@T  device call raises during admission -> slot rolled
                   back, retry succeeds, streams exact
  decode_raise@T   device call raises during the tick -> _dstate
                   resyncs from mirrors, retry re-runs idempotently
  queue_flood      max_queue overflow -> BackpressureError (reject) /
                   oldest evicted (shed_oldest); admitted streams exact
  cancel_deadline  mid-decode cancel + tick deadline -> "cancelled" /
                   "timeout", survivors exact

Speculative-decode scenarios (docs/serving.md "Speculative decoding"):
  spec_draft_nan@T:S nan injected into slot S's DRAFT logits on a spec
                   engine -> the slot DEGRADES to non-spec decode for
                   that tick (acceptance 0), is NEVER quarantined, and
                   every stream stays bit-identical to the non-spec
                   baseline; exactly-once + trace ceilings hold
  spec_nan_logits@T:S nan in the TARGET logits on a spec engine -> the
                   quarantine verdict still rides the emission matrix:
                   only slot S poisons, survivors exact

Quantized-engine scenario (weight-only int8 serving, docs/serving.md
"Quantized serving"):
  quant_nan_logits@T:S nan_logits on a quant="int8" engine -> only
                   slot S's request ends "poisoned", survivors are
                   bit-identical to the fault-free QUANT baseline
                   (the quant engine's own parity class), the
                   serving.quant_matmuls counter moved (the int8 path
                   actually served), exactly-once + trace ceilings

Router scenario (the replicated-engine router, inference/router.py;
docs/serving.md "Sharded serving & routing"):
  router_replica_death 2 engine replicas, one killed mid-decode ->
                   its un-terminal requests requeue and REPLAY on the
                   survivor; every request still resolves exactly
                   once, final streams are bit-identical to the
                   fault-free run (at-least-once delivery, exactly-
                   once resolution), the survivor holds its trace
                   ceilings, and the death leaves a flight dump

Fleet scenarios (autoscaling + live migration + preemption tolerance,
inference/autoscale.py; docs/serving.md "Autoscaling & live
migration"):
  autoscale_flood  a request flood on a 1-replica fleet under the
                   Autoscaler -> replicas scale out toward max, then
                   drain back to min when idle; every request resolves
                   exactly once, streams bit-identical, scale
                   decisions leave parseable flight dumps
  live_migration   kill a paged-KV replica mid-decode with migration
                   ON -> every live stream moves through a host KV
                   snapshot (ZERO re-prefill: the survivor's prefill
                   trace count does not move), zero replays, streams
                   bit-identical to the fault-free run
  serving_device_loss a tp=2 engine under EnginePreemptGuard loses a
                   device (replica_preempt fault) -> tp degrades via
                   the planner, the engine rebuilds on the survivor
                   mesh with live streams migrated in place, streams
                   stay bit-identical and the trace ceilings hold

Disaggregation scenarios (host-tier KV + prefill/decode role split,
inference/host_kv.py + router roles; docs/serving.md
"Disaggregation"):
  host_spill_flood shared-prefix families oversubscribe a tiny paged
                   pool on a host-tiered engine -> evicted registered
                   pages SPILL to host ndarrays and SWAP back in on
                   the next family hit (spills > 0, swapins > 0),
                   streams bit-identical to a tier-less twin, and the
                   memory ledger's kv_pool_host row tracks the tier's
                   live bytes
  prefill_role_death a roles=["prefill","decode"] fleet loses its
                   only prefill replica AFTER handoffs started -> new
                   submissions still admit (roles are placement
                   preferences, availability beats specialization:
                   the decode survivor picks up prefill duty), every
                   stream resolves "length"/"eos" bit-identical, and
                   the death leaves a router_replica_death flight dump

Paged-KV scenarios (the block-pool layout, docs/serving.md "Paged KV
cache"):
  paged_pool_flood more demand than pages -> later requests WAIT for
                   pages (never a wedged slot), every stream completes
                   bit-identical, zero pages/reservations leak
  paged_nan_poison nan_logits on the paged engine -> the poisoned
                   slot's pages free (pages_in_use drains to 0),
                   survivors exact
  cow_raise@T      the copy-on-write page copy raises -> admission
                   rolls back (shared refcounts released), retry
                   succeeds, the sharer's stream stays exact

Overload-resilience scenarios (multi-tenant admission + brownout +
the request journal, inference/admission.py / brownout.py /
journal.py; docs/serving.md "Tenancy, brownout & durability"):
  tenant_flood     a rate-limited tenant floods (quota_flood fault:
                   the router self-injects low-priority flood
                   submissions mid-drill) -> the flood is quota-
                   rejected past its burst, every paying-tenant
                   stream completes bit-identical, and every
                   rejection resolves terminally (no limbo, no trace
                   leak)
  brownout_ladder  a sustained SLO burn on an injected clock drives
                   the full 0 -> 3 escalation (spec drafts off,
                   lowest class suspended to host KV, oldest pending
                   shed) and the clear drives 3 -> 0 level-by-level;
                   streams stay bit-identical (the ladder degrades
                   capacity, never correctness) and every transition
                   leaves a brownout_escalate / brownout_recover
                   flight dump
  process_crash_replay a subprocess builds a JOURNALED router, is
                   SIGKILLed mid-decode (sigkill fault: a real
                   os.kill, no flush, no atexit), and the parent
                   recovers a fresh router over the same journal_dir
                   -> every journal-accepted request reaches EXACTLY
                   one terminal event across both processes
                   (at-least-once prefill, exactly-once resolution),
                   and every replayed greedy stream is bit-identical
                   to the fault-free run

Observability requirements (every scenario, the PR-3 "parseable black
box" pattern extended to serving): a parseable serving-telemetry JSONL
with >= 1 serving_tick record (profiler/serving_telemetry — engines in
scenarios stream to <scenario>/telemetry.jsonl) and >= 1 COMPLETE
request trace (queue + prefill + decode + exactly one terminal span,
profiler/tracing). The nan_logits and router_replica_death scenarios
additionally feed their outcome into an SLO burn-rate monitor
(profiler/slo) with a tight error budget and require the alert to fire
AND leave a parseable slo_burn_alert flight dump.

Usage:
  python tools/chaos_serving.py            # the full drill
  python tools/chaos_serving.py --quick    # smaller workload (CI)
  python tools/chaos_serving.py --bench    # guardrail overhead JSON
"""
from __future__ import annotations

import argparse
import json
import math
import os
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

# CPU unconditionally: the axon tunnel flaps and ANY backend init then
# hangs (CLAUDE.md trap); the drill's assertions are platform-free.
# 4 virtual devices: serving_device_loss needs a tp-sharded mesh to
# preempt; the single-engine scenarios just run on device 0.
from paddle_tpu.device import pin_cpu            # noqa: E402
pin_cpu(4)

import numpy as np                               # noqa: E402
import jax                                       # noqa: E402
import jax.numpy as jnp                          # noqa: E402


def _log(msg):
    print(f"[chaos_serving] {msg}", flush=True)


# ------------------------------------------------------------- fixture
def build_model(hidden=32, layers=2, vocab=64):
    from paddle_tpu.models.gpt import GPTConfig, init_gpt_params
    cfg = GPTConfig(vocab_size=vocab, hidden_size=hidden,
                    num_layers=layers, num_heads=max(hidden // 16, 1),
                    ffn_hidden=2 * hidden, max_seq_len=128,
                    sequence_parallel=False, remat=False,
                    dtype=jnp.float32)
    return init_gpt_params(cfg, jax.random.PRNGKey(0)), cfg


def build_workload(n, lo, hi, vocab, seed=0):
    rng = np.random.RandomState(seed)
    lens = rng.randint(lo, hi + 1, n)
    return [rng.randint(0, vocab, L).astype(np.int32) for L in lens]


# per-scenario observability context: every engine a scenario builds
# streams its serving_tick JSONL into the scenario dir and emits
# request-scoped traces, and the drill REQUIRES both to be present and
# parseable (the PR-3 "chaos requires a parseable black box" pattern
# extended to serving telemetry + traces)
_SCEN = {"tele": None, "engines": []}


def make_engine(params, cfg, max_len, **kw):
    from paddle_tpu.inference.serving import ServingEngine
    kw.setdefault("num_slots", 3)
    kw.setdefault("telemetry_jsonl", _SCEN["tele"])
    kw.setdefault("tracing", True)
    eng = ServingEngine(params, cfg, family="gpt", max_len=max_len, **kw)
    _SCEN["engines"].append(eng)
    return eng


def make_router(params, cfg, max_len, **kw):
    from paddle_tpu.inference.router import create_router
    router = create_router(params, cfg, max_len=max_len, tracing=True,
                           telemetry_jsonl=_SCEN["tele"], **kw)
    for rep in router.replicas:
        _SCEN["engines"].append(rep.eng)
    return router


# ------------------------------------------------------------ checking
def check_terminal(reqs):
    """Invariant 1: exactly-once terminal resolution."""
    from paddle_tpu.inference.serving import TERMINAL_REASONS
    for r in reqs:
        if not r.done:
            return f"request {r.id} not done (limbo)"
        if r.finish_reason not in TERMINAL_REASONS:
            return (f"request {r.id} finish_reason "
                    f"{r.finish_reason!r} not terminal")
        if r.slot is not None:
            return f"request {r.id} resolved but still owns slot {r.slot}"
    return None

def check_streams(reqs, baseline, full_reasons=("length", "eos")):
    """Invariant 2: survivors bit-identical, early exits exact
    prefixes. `baseline[i]` is request i's fault-free stream."""
    for i, r in enumerate(reqs):
        got = np.asarray(r.tokens, np.int32)
        want = baseline[i]
        if r.finish_reason in full_reasons:
            if not np.array_equal(got, want):
                return (f"request {i} ({r.finish_reason}) diverged: "
                        f"{got.tolist()} vs {want.tolist()}")
        else:
            if not np.array_equal(got, want[:len(got)]):
                return (f"request {i} ({r.finish_reason}) is not a "
                        f"prefix of its fault-free stream: "
                        f"{got.tolist()} vs {want.tolist()}")
    return None


def check_traces(eng):
    """Invariant 3b: guardrails cost no recompiles."""
    dec, pre = eng.trace_counts()
    ceiling = 2 * max(int(math.log2(eng.max_len)), 1)
    if dec > 2:
        return f"decode traces {dec} > 2"
    if pre > ceiling:
        return f"prefill traces {pre} > {ceiling}"
    return None


def check_flight(fdir, want_reason=None):
    """Invariant 3a: eventful faults leave a parseable black box.
    `want_reason` additionally requires a dump whose reason matches
    (e.g. the SLO monitor's "slo_burn_alert")."""
    from paddle_tpu.profiler.flight_recorder import load_dump
    names = sorted(f for f in (os.listdir(fdir) if os.path.isdir(fdir)
                               else []) if f.endswith(".json"))
    if not names:
        return f"no flight dump under {fdir}"
    reasons = set()
    for name in names:
        try:
            doc = load_dump(os.path.join(fdir, name))
        except (OSError, ValueError) as e:
            return f"flight dump {name} unparseable: {e}"
        if "monitor" not in doc:
            return f"flight dump {name}: no monitor snapshot"
        reasons.add(doc.get("reason"))
    if want_reason is not None and want_reason not in reasons:
        return (f"no {want_reason!r} flight dump (reasons: "
                f"{sorted(r for r in reasons if r)})")
    return None


def check_telemetry(tele_path):
    """Observability invariant A: every scenario leaves a parseable
    serving-telemetry JSONL with >= 1 serving_tick record (router
    scenarios fan out to <path>.r<i> — any replica's file counts)."""
    import glob
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from telemetry_report import summarize
    paths = sorted(glob.glob(tele_path + "*"))
    if not paths:
        return f"no serving-telemetry JSONL at {tele_path}*"
    ticks = 0
    for p in paths:
        try:
            doc = summarize(p)
        except Exception as e:                     # noqa: BLE001
            return f"telemetry JSONL {p} unparseable: {e}"
        ticks += (doc.get("serving_ticks") or {}).get("ticks", 0)
    if ticks == 0:
        return f"no serving_tick records under {tele_path}*"
    return None


def check_request_trace():
    """Observability invariant B: >= 1 COMPLETE request trace — a
    span tree with queue + prefill + decode spans and EXACTLY one
    terminal span (profiler/tracing; the scenario cleared the tracer
    on entry, so these traces are its own)."""
    from paddle_tpu.profiler import tracing
    tr = tracing.tracer()
    seen = 0
    for tid in tr.trace_ids():
        spans = tr.spans(tid)
        names = {s.name for s in spans}
        terms = [s for s in spans if s.kind == "terminal"]
        if len(terms) > 1:
            return f"trace {tid} has {len(terms)} terminal spans"
        if len(terms) == 1 and {"queue", "prefill", "decode"} <= names:
            seen += 1
    if not seen:
        return ("no complete request trace "
                "(queue+prefill+decode+terminal)")
    return None


def check_burn_alert(fdir, stream, bad, total):
    """Observability invariant C (nan_logits / router_replica_death):
    feeding the scenario's outcome into an SLO burn-rate monitor with
    a tight error budget fires an alert, and the alert leaves a
    parseable slo_burn_alert flight dump."""
    from paddle_tpu.profiler.slo import BurnRateMonitor, Objective
    mon = BurnRateMonitor(
        [Objective(f"{stream}_rate", stream, "event", budget=0.001)],
        pairs=((60.0, 5.0),), cooldown_s=0.0)
    mon.observe_events(stream, bad=bad, total=total)
    alerts = mon.check()
    if not alerts:
        return (f"burn-rate monitor fired no alert for {bad}/{total} "
                f"bad {stream} events at budget 0.001")
    return check_flight(fdir, want_reason="slo_burn_alert")


# ------------------------------------------------------------ the drill
def run_drill(quick: bool = False, keep_root: bool = False) -> int:
    from paddle_tpu.inference.serving import BackpressureError
    from paddle_tpu.profiler import flight_recorder, monitor
    from paddle_tpu.testing import faults

    t_start = time.time()
    n_req, gen = (6, 6) if quick else (10, 10)
    params, cfg = build_model()
    max_len = 64
    prompts = build_workload(n_req, 3, 20, cfg.vocab_size)
    root = tempfile.mkdtemp(prefix="chaos_serving_")
    failures = []

    # fault-free baseline: per-request streams (bit-parity makes these
    # independent of pool size / join order, which is exactly what the
    # scenarios below re-assert under faults)
    eng = make_engine(params, cfg, max_len)
    base_reqs = [eng.submit(p, gen) for p in prompts]
    eng.drain()
    err = check_terminal(base_reqs) or check_traces(eng)
    if err:
        _log(f"baseline FAILED: {err}")
        return 2
    baseline = [np.asarray(r.tokens, np.int32) for r in base_reqs]
    _log(f"baseline: {n_req} requests x {gen} tokens ok")

    rec = flight_recorder.recorder()

    def scenario(name, body, spec=None, want_flight=True):
        from paddle_tpu.profiler import tracing
        sdir = os.path.join(root, name)
        fdir = os.path.join(sdir, "flight")
        os.makedirs(fdir, exist_ok=True)
        rec.clear()
        rec.set_dir(fdir)
        tracing.clear()
        _SCEN["tele"] = os.path.join(sdir, "telemetry.jsonl")
        _SCEN["engines"] = []
        if spec:
            faults.install(spec, once_dir=os.path.join(sdir, "once"))
        t0 = time.time()
        try:
            err = body()
        finally:
            if spec:
                faults.uninstall()
            for eng in _SCEN["engines"]:
                try:
                    eng.flush_telemetry(timeout=10)
                except Exception:                  # noqa: BLE001
                    pass
            tele_path, _SCEN["tele"] = _SCEN["tele"], None
            _SCEN["engines"] = []
            rec.set_dir(None)
        if err is None and want_flight:
            err = check_flight(fdir)
        # every scenario must leave a parseable serving-telemetry
        # JSONL and >= 1 complete request trace (the PR-3 black-box
        # requirement extended to the serving observability layer)
        if err is None:
            err = check_telemetry(tele_path) or check_request_trace()
        tag = "FAIL" if err else "ok"
        _log(f"{name:<28} {tag}  ({time.time() - t0:.1f}s)")
        if err:
            failures.append(f"{name}: {err}")

    # --- nan_logits: poisoned-slot quarantine isolation -------------
    def nan_body():
        eng = make_engine(params, cfg, max_len)
        reqs = [eng.submit(p, gen) for p in prompts]
        eng.drain()
        reasons = [r.finish_reason for r in reqs]
        if reasons.count("poisoned") != 1:
            return f"expected exactly one poisoned request: {reasons}"
        err = (check_terminal(reqs) or check_streams(reqs, baseline)
               or check_traces(eng))
        if err:
            return err
        # the poisoned finish burns the error budget: the SLO monitor
        # must alert and leave a parseable slo_burn_alert flight dump
        fdir = os.path.join(root, "nan_logits@2:1", "flight")
        return check_burn_alert(fdir, "errors",
                                reasons.count("poisoned"), len(reqs))
    scenario("nan_logits@2:1", nan_body, spec="nan_logits@2:1")

    # --- tick_stall: watchdog budget/backoff recovery ----------------
    def stall_body():
        r0 = monitor.counter("serving.retries").value
        eng = make_engine(params, cfg, max_len, watchdog_timeout=0.1,
                          retries=3, backoff_base=0.2)
        reqs = [eng.submit(p, gen) for p in prompts]
        eng.drain()
        if monitor.counter("serving.retries").value <= r0:
            return "watchdog never retried (stall not exercised)"
        return (check_terminal(reqs) or check_streams(reqs, baseline)
                or check_traces(eng))
    scenario("tick_stall@2:400", stall_body, spec="tick_stall@2:400")

    # --- raise-mid-prefill / raise-mid-decode: self-healing tick -----
    def raise_body(spec_kind):
        def body():
            f0 = monitor.counter("serving.faults").value
            eng = make_engine(params, cfg, max_len)
            reqs = [eng.submit(p, gen) for p in prompts]
            eng.drain()
            if monitor.counter("serving.faults").value <= f0:
                return "fault never fired"
            err = check_terminal(reqs) or check_traces(eng)
            if err:
                return err
            # the retry makes the fault fully transparent: EVERY
            # stream completes and matches
            if any(r.finish_reason != "length" for r in reqs):
                return ("retry was not transparent: "
                        f"{[r.finish_reason for r in reqs]}")
            return check_streams(reqs, baseline)
        return body
    scenario("prefill_raise@0", raise_body("prefill"),
             spec="prefill_raise@0")
    scenario("decode_raise@2", raise_body("decode"),
             spec="decode_raise@2")

    # --- oom: forensics black box + transparent recovery -------------
    def oom_body():
        o0 = monitor.counter("serving.oom_forensics").value
        eng = make_engine(params, cfg, max_len)
        reqs = [eng.submit(p, gen) for p in prompts]
        eng.drain()
        if monitor.counter("serving.oom_forensics").value <= o0:
            return "oom fault never fired (no forensics dump)"
        err = check_terminal(reqs) or check_traces(eng)
        if err:
            return err
        # the injected RESOURCE_EXHAUSTED rides the decode retry path,
        # so recovery is transparent (exactly-once fire + bit-exact
        # streams) — the forensics dump is pure observation
        if any(r.finish_reason != "length" for r in reqs):
            return ("oom recovery was not transparent: "
                    f"{[r.finish_reason for r in reqs]}")
        err = check_streams(reqs, baseline)
        if err:
            return err
        # the black box itself: parseable, with a non-empty live-array
        # census AND a component-attributed ledger
        fdir = os.path.join(root, "oom@2", "flight")
        err = check_flight(fdir, want_reason="oom_forensics")
        if err:
            return err
        from paddle_tpu.profiler.flight_recorder import load_dump
        for name in sorted(os.listdir(fdir)):
            doc = load_dump(os.path.join(fdir, name))
            if doc.get("reason") != "oom_forensics":
                continue
            oom = (doc.get("config") or {}).get("oom_forensics") or {}
            if not oom.get("census"):
                return "oom_forensics dump has an empty census"
            led = oom.get("ledger") or {}
            if not led.get("components") or not led.get("total"):
                return "oom_forensics dump has an empty ledger"
            return None
        return "no oom_forensics dump under the scenario flight dir"
    scenario("oom@2", oom_body, spec="oom@2")

    # --- queue flood: backpressure under both policies ---------------
    def flood_reject():
        eng = make_engine(params, cfg, max_len, num_slots=2, max_queue=2)
        accepted, rejected = [], 0
        for i, p in enumerate(prompts):
            try:
                accepted.append((i, eng.submit(p, gen)))
            except BackpressureError as e:
                rejected += 1
                if e.queue_depth < 2:
                    return f"rejected at depth {e.queue_depth} < max_queue"
        if rejected == 0:
            return "queue flood never tripped backpressure"
        eng.drain()
        reqs = [r for _, r in accepted]
        err = check_terminal(reqs) or check_traces(eng)
        if err:
            return err
        for i, r in accepted:
            if not np.array_equal(np.asarray(r.tokens, np.int32),
                                  baseline[i]):
                return f"accepted request {i} diverged under flood"
        return None
    scenario("queue_flood_reject", flood_reject, want_flight=False)

    def flood_shed():
        eng = make_engine(params, cfg, max_len, num_slots=2, max_queue=2,
                          queue_policy="shed_oldest")
        reqs = [eng.submit(p, gen) for p in prompts]  # never raises
        eng.drain()
        err = check_terminal(reqs) or check_traces(eng)
        if err:
            return err
        shed = [r for r in reqs if r.finish_reason == "evicted"]
        if not shed:
            return "shed_oldest never shed"
        return check_streams(reqs, baseline)
    scenario("queue_flood_shed", flood_shed, want_flight=False)

    # --- paged KV: pool exhaustion under flood -----------------------
    def paged_flood():
        # ~3 requests' worth of pages for the whole flood: later
        # requests must WAIT for pages (head-of-line), admit as
        # earlier ones free, and complete bit-identical — never a
        # wedged slot, never a leaked page
        eng = make_engine(params, cfg, max_len, num_slots=4,
                          kv_layout="paged", page_size=8, num_pages=13)
        reqs = [eng.submit(p, gen) for p in prompts]
        eng.drain()
        err = check_terminal(reqs) or check_traces(eng)
        if err:
            return err
        st = eng.pool_stats()
        if st["pages_in_use"] or st["pages_reserved"]:
            return f"pool leaked after flood: {st}"
        if any(r.finish_reason not in ("length", "eos") for r in reqs):
            return ("flood evicted instead of queueing: "
                    f"{[r.finish_reason for r in reqs]}")
        return check_streams(reqs, baseline)
    scenario("paged_pool_flood", paged_flood, want_flight=False)

    # --- paged KV: poisoned slot frees its pages ---------------------
    def paged_poison():
        eng = make_engine(params, cfg, max_len, kv_layout="paged",
                          page_size=8)
        reqs = [eng.submit(p, gen) for p in prompts]
        eng.drain()
        reasons = [r.finish_reason for r in reqs]
        if reasons.count("poisoned") != 1:
            return f"expected exactly one poisoned request: {reasons}"
        st = eng.pool_stats()
        if st["pages_in_use"] or st["pages_reserved"]:
            return f"poisoned slot leaked pages: {st}"
        return (check_terminal(reqs) or check_streams(reqs, baseline)
                or check_traces(eng))
    scenario("paged_nan_poison@2:1", paged_poison, spec="nan_logits@2:1")

    # --- paged KV: COW page-copy fault -------------------------------
    # the dense reference runs OUTSIDE the fault window (its ticks
    # would consume the once-only fault marker)
    aligned = build_workload(1, 16, 16, cfg.vocab_size, seed=99)[0]
    aligned_want = make_engine(params, cfg, max_len).generate(
        [aligned], gen)[0]

    def cow_fault():
        want = aligned_want
        f0 = monitor.counter("serving.faults").value
        eng = make_engine(params, cfg, max_len, kv_layout="paged",
                          page_size=8)
        donor = eng.submit(aligned, gen)
        eng.drain()                  # donor registers its full pages
        sharer = eng.submit(aligned, gen)   # aligned full match -> COW
        eng.drain()
        if monitor.counter("serving.faults").value <= f0:
            return "cow fault never fired"
        err = check_terminal([donor, sharer]) or check_traces(eng)
        if err:
            return err
        if sharer.finish_reason != "length":
            return ("cow retry was not transparent: "
                    f"{sharer.finish_reason!r}")
        for r in (donor, sharer):
            if not np.array_equal(np.asarray(r.tokens, np.int32), want):
                return "stream diverged across the cow fault"
        st = eng.pool_stats()
        if st["pages_reserved"]:
            return f"cow fault leaked reservations: {st}"
        return None
    scenario("cow_raise@0", cow_fault, spec="cow_raise@0")

    # --- speculative decode: draft nan degrades, never quarantines ---
    def spec_draft_nan():
        eng = make_engine(params, cfg, max_len, spec_decode="spec",
                          gamma=3, draft_layers=cfg.num_layers)
        reqs = [eng.submit(p, gen) for p in prompts]
        eng.drain()
        if any(r.finish_reason == "poisoned" for r in reqs):
            return ("draft nan quarantined the target stream: "
                    f"{[r.finish_reason for r in reqs]}")
        err = check_terminal(reqs) or check_traces(eng)
        if err:
            return err
        if any(r.finish_reason != "length" for r in reqs):
            return ("degrade was not transparent: "
                    f"{[r.finish_reason for r in reqs]}")
        # full-depth self-draft accepts everything EXCEPT the poisoned
        # tick — a clean acceptance ledger means the fault never bit
        if eng._spec_acc_total >= eng._spec_prop_total:
            return "draft fault never degraded acceptance"
        # streams equal the NON-SPEC baseline: speculation's bit-parity
        # AND the degrade in one assertion
        return check_streams(reqs, baseline)
    scenario("spec_draft_nan@2:1", spec_draft_nan,
             spec="draft_nan@2:1", want_flight=False)

    # --- speculative decode: target nan still quarantines exactly ----
    def spec_target_nan():
        eng = make_engine(params, cfg, max_len, spec_decode="spec",
                          gamma=3, draft_layers=cfg.num_layers)
        reqs = [eng.submit(p, gen) for p in prompts]
        eng.drain()
        reasons = [r.finish_reason for r in reqs]
        if reasons.count("poisoned") != 1:
            return f"expected exactly one poisoned request: {reasons}"
        return (check_terminal(reqs) or check_streams(reqs, baseline)
                or check_traces(eng))
    scenario("spec_nan_logits@2:1", spec_target_nan,
             spec="nan_logits@2:1")

    # --- quantized engine: quarantine + exactly-once still hold ------
    # the quantized engine's streams are its OWN parity class (weight-
    # only dequant shifts logits vs fp by the recorded budget), so the
    # survivors compare against a fault-free QUANT baseline, not the
    # fp one — the guardrail claim is isolation, not fp equality
    quant_want = make_engine(params, cfg, max_len,
                             quant="int8").generate(prompts, gen)

    def quant_nan():
        from paddle_tpu.profiler import monitor
        q0 = monitor.counter("serving.quant_matmuls").value
        eng = make_engine(params, cfg, max_len, quant="int8")
        reqs = [eng.submit(p, gen) for p in prompts]
        eng.drain()
        reasons = [r.finish_reason for r in reqs]
        if reasons.count("poisoned") != 1:
            return f"expected exactly one poisoned request: {reasons}"
        if monitor.counter("serving.quant_matmuls").value <= q0:
            return "quant_matmuls counter never moved (fp path served?)"
        return (check_terminal(reqs)
                or check_streams(reqs, quant_want)
                or check_traces(eng))
    scenario("quant_nan_logits@2:1", quant_nan, spec="nan_logits@2:1")

    # --- router: replica death mid-decode ----------------------------
    def replica_death():
        from paddle_tpu.inference.serving import TERMINAL_REASONS
        r0 = monitor.counter("serving.router.requeues").value
        router = make_router(params, cfg, max_len, replicas=2,
                             family="gpt", num_slots=3,
                             concurrent=False)     # deterministic drill
        reqs = [router.submit(p, gen) for p in prompts]
        for _ in range(3):
            router.step()                 # streams mid-decode on BOTH
        killed = router.kill_replica(0)
        if killed == 0:
            return "kill_replica(0) found nothing to requeue"
        if monitor.counter("serving.router.requeues").value <= r0:
            return "requeues counter never moved"
        router.drain()
        # invariant 1 on the OUTER requests (exactly-once terminal)
        for r in reqs:
            if not r.done:
                return f"request {r.id} not done (limbo)"
            if r.finish_reason not in TERMINAL_REASONS:
                return (f"request {r.id} finish_reason "
                        f"{r.finish_reason!r} not terminal")
            if r.slot is not None:
                return f"request {r.id} resolved but owns a slot"
        # migration semantics: every request COMPLETES (requeued ones
        # replay from scratch on the survivor) and final streams are
        # bit-identical to the fault-free run — at-least-once token
        # delivery, exactly-once resolution, exact final streams
        if any(r.finish_reason not in ("length", "eos") for r in reqs):
            return ("death was not transparent: "
                    f"{[r.finish_reason for r in reqs]}")
        if not any(r.requeues for r in reqs):
            return "no surviving request records a requeue"
        err = check_streams(reqs, baseline)
        if err:
            return err
        st = router.stats()
        if st["replicas_live"] != 1:
            return f"expected 1 live replica: {st}"
        # the survivor's engine must hold its trace ceilings through
        # the requeue wave (migration costs no recompiles)
        err = check_traces(router.replicas[1].eng)
        if err:
            return err
        # trace-context propagation across the death: the replayed
        # requests' traces carry a severed subtree + a replay link and
        # still end in EXACTLY one terminal span
        from paddle_tpu.profiler import tracing as _tracing
        tr = _tracing.tracer()
        replayed = [r for r in reqs if r.requeues]
        for r in replayed:
            spans = tr.spans(r.trace.trace_id)
            names = [s.name for s in spans]
            if "severed" not in names or "replay" not in names:
                return (f"request {r.id}: replayed trace lacks "
                        f"severed/replay marks: {sorted(set(names))}")
            terms = [s for s in spans if s.kind == "terminal"]
            if len(terms) != 1:
                return (f"request {r.id}: {len(terms)} terminal "
                        "spans after replay")
        # the requeue churn burns the budget: alert + parseable dump
        fdir = os.path.join(root, "router_replica_death", "flight")
        return check_burn_alert(fdir, "requeues", killed, len(reqs))
    scenario("router_replica_death", replica_death)

    # --- cancel + deadlines ------------------------------------------
    def cancel_deadline():
        eng = make_engine(params, cfg, max_len)
        reqs = []
        for i, p in enumerate(prompts):
            reqs.append(eng.submit(
                p, gen, deadline_ticks=3 if i == 1 else None))
        eng.step()
        eng.step()
        victim = next(r for r in reqs if r.slot is not None
                      and r.finish_reason is None and r is not reqs[1])
        if not victim.cancel():
            return "cancel() returned False on a live request"
        eng.drain()
        err = check_terminal(reqs) or check_streams(reqs, baseline)
        if err:
            return err
        if victim.finish_reason != "cancelled":
            return f"victim finished {victim.finish_reason!r}"
        if reqs[1].finish_reason != "timeout":
            return f"deadline request finished {reqs[1].finish_reason!r}"
        return None
    scenario("cancel_deadline", cancel_deadline, want_flight=False)

    # --- autoscaler: flood scales out, idle drains back to min -------
    def autoscale_flood():
        from paddle_tpu.inference.autoscale import (AutoscaleConfig,
                                                    Autoscaler)
        t = [0.0]
        router = make_router(params, cfg, max_len, replicas=1,
                             family="gpt", num_slots=2,
                             concurrent=False, clock=lambda: t[0])
        scaler = Autoscaler(
            router, spawn=lambda: make_engine(params, cfg, max_len,
                                              num_slots=2),
            cfg=AutoscaleConfig(min_replicas=1, max_replicas=3,
                                breach_ticks=2, idle_ticks=3,
                                cooldown_s=1.0),
            clock=lambda: t[0])
        reqs = [router.submit(p, gen) for p in prompts]
        peak = 1
        for _ in range(200):
            if not router.has_work():
                break
            router.step()
            t[0] += 2.0
            scaler.tick()
            peak = max(peak, len(router.dispatchable()))
        if router.has_work():
            return "flood never drained"
        if peak < 2:
            return f"flood never scaled out (peak {peak})"
        for _ in range(30):                  # idle: drain back to min
            if len(router.dispatchable()) == 1:
                break
            router.step()
            t[0] += 2.0
            scaler.tick()
        if len(router.dispatchable()) != 1:
            return (f"idle fleet never scaled back to min "
                    f"({len(router.dispatchable())} dispatchable)")
        err = (check_terminal(reqs) or check_streams(reqs, baseline))
        if err:
            return err
        if any(r.finish_reason not in ("length", "eos") for r in reqs):
            return ("scaling was not transparent: "
                    f"{[r.finish_reason for r in reqs]}")
        for rep in router.replicas:
            if rep.alive:
                err = check_traces(rep.eng)
                if err:
                    return err
        fdir = os.path.join(root, "autoscale_flood", "flight")
        return (check_flight(fdir, want_reason="autoscale_scale_out")
                or check_flight(fdir, want_reason="autoscale_scale_in"))
    scenario("autoscale_flood", autoscale_flood, want_flight=False)

    # --- live migration: replica death moves streams, ZERO re-prefill
    def live_migration():
        mig0 = monitor.counter("serving.autoscale.migrations").value
        fb0 = monitor.counter(
            "serving.autoscale.migrate_fallbacks").value
        router = make_router(params, cfg, max_len, replicas=2,
                             family="gpt", num_slots=6,
                             concurrent=False, kv_layout="paged",
                             page_size=8)
        reqs = [router.submit(p, gen) for p in prompts]
        for _ in range(3):
            router.step()                 # streams mid-decode on BOTH
        victim = max(router.replicas,
                     key=lambda rep: sum(1 for o in rep.inner.values()
                                         if not o.done)).idx
        live = sum(1 for o in router.replicas[victim].inner.values()
                   if not o.done)
        if live == 0:
            return "nothing live on the victim (drill too short)"
        survivor = router.replicas[1 - victim].eng
        pre_prefills = survivor.trace_counts()[1]
        replayed = router.kill_replica(victim)
        if replayed != 0:
            return (f"{replayed} requests fell back to replay "
                    "(every stream should migrate)")
        moved = (monitor.counter("serving.autoscale.migrations").value
                 - mig0)
        if moved < live:
            return f"only {moved}/{live} live streams migrated"
        if monitor.counter(
                "serving.autoscale.migrate_fallbacks").value != fb0:
            return "migrate_fallbacks moved on the migration-only path"
        router.drain()
        # THE migration claim: zero re-prefilled tokens — the survivor
        # ran no prefill for the adopted streams (its prefill trace
        # count is unchanged), and no request records a requeue
        if survivor.trace_counts()[1] != pre_prefills:
            return (f"survivor re-prefilled: {pre_prefills} -> "
                    f"{survivor.trace_counts()[1]} prefill traces")
        if any(r.requeues for r in reqs):
            return "a migrated stream recorded a requeue (replay path)"
        err = (check_terminal(reqs) or check_streams(reqs, baseline)
               or check_traces(survivor))
        if err:
            return err
        if any(r.finish_reason not in ("length", "eos") for r in reqs):
            return ("migration was not transparent: "
                    f"{[r.finish_reason for r in reqs]}")
        fdir = os.path.join(root, "live_migration", "flight")
        return check_flight(fdir, want_reason="router_replica_death")
    scenario("live_migration", live_migration, want_flight=False)

    # --- device loss: tp degrade + in-place stream migration ---------
    def device_loss():
        from paddle_tpu.inference.autoscale import EnginePreemptGuard
        from paddle_tpu.parallel.mesh import build_mesh
        devs = jax.devices()
        if len(devs) < 2:
            return f"need >= 2 devices for a tp mesh, got {len(devs)}"
        mesh = build_mesh({"tp": 2}, devices=devs[:2])
        eng = make_engine(params, cfg, max_len, num_slots=4, mesh=mesh)
        guard = EnginePreemptGuard(eng, lease_timeout_s=0.05)
        reqs = [eng.submit(p, gen) for p in prompts]
        new_tp = 0
        for _ in range(200):
            if not eng.has_work():
                break
            eng.step()
            new_tp = max(new_tp, guard.poll())
        if eng.has_work():
            return "engine never drained after the preemption"
        if new_tp != 1:
            return f"guard never degraded tp (poll() -> {new_tp})"
        if int(np.prod(eng.mesh.devices.shape)) != 1:
            return f"engine not rebuilt on the survivor mesh: {eng.mesh}"
        err = (check_terminal(reqs) or check_streams(reqs, baseline)
               or check_traces(eng))
        if err:
            return err
        if any(r.finish_reason not in ("length", "eos") for r in reqs):
            return ("preemption was not transparent: "
                    f"{[r.finish_reason for r in reqs]}")
        fdir = os.path.join(root, "serving_device_loss", "flight")
        return check_flight(fdir, want_reason="serving_preempt")
    scenario("serving_device_loss", device_loss,
             spec="replica_preempt@3:1", want_flight=False)

    # --- host_spill_flood: prefix reuse beyond the device pool -------
    def host_spill_flood():
        # shared-prefix families deliberately oversubscribe a tiny
        # device pool: every evicted REGISTERED page must spill to the
        # host tier and come back as a swap-in on the next family hit,
        # with streams bit-identical to a tier-less engine
        rng = np.random.RandomState(11)
        fam_prompts = []
        for _ in range(3):
            head = rng.randint(1, cfg.vocab_size - 1, 16).astype(np.int32)
            for _ in range(2):
                fam_prompts.append(np.concatenate(
                    [head, rng.randint(1, cfg.vocab_size - 1,
                                       4).astype(np.int32)]))
        kw = dict(num_slots=1, kv_layout="paged", page_size=8,
                  num_pages=6, prefix_sharing=True)
        plain = make_engine(params, cfg, max_len, **kw)
        tiered = make_engine(params, cfg, max_len,
                             host_kv_bytes=1 << 20, **kw)
        local_base = None
        for _ in range(2):                    # round 2 re-hits the tier
            base_reqs = [plain.submit(p, gen) for p in fam_prompts]
            plain.drain()
            local_base = [np.asarray(r.tokens, np.int32)
                          for r in base_reqs]
            reqs = [tiered.submit(p, gen) for p in fam_prompts]
            tiered.drain()
            err = (check_terminal(reqs)
                   or check_streams(reqs, local_base)
                   or check_traces(tiered))
            if err:
                return err
        st = tiered.pool_stats()["host_tier"]
        if st["spills"] == 0:
            return f"device pool never spilled to host: {st}"
        if st["swapins"] == 0:
            return f"host tier never served a swap-in: {st}"
        led = tiered.memory_ledger()
        if led["components"]["kv_pool_host"] != st["bytes"]:
            return ("ledger kv_pool_host "
                    f"{led['components']['kv_pool_host']} != tier "
                    f"bytes {st['bytes']}")
        return None
    scenario("host_spill_flood", host_spill_flood, want_flight=False)

    # --- prefill_role_death: disagg fleet loses its prefill replica --
    def prefill_role_death():
        h0 = monitor.counter("serving.router.handoffs").value
        router = make_router(params, cfg, max_len, replicas=2,
                             family="gpt", num_slots=4,
                             concurrent=False,
                             roles=["prefill", "decode"])
        half = len(prompts) // 2
        reqs = [router.submit(p, gen) for p in prompts[:half]]
        for _ in range(60):            # prefill + first handoffs land
            router.step()
            if monitor.counter("serving.router.handoffs").value > h0:
                break
        if monitor.counter("serving.router.handoffs").value <= h0:
            return "no prefill->decode handoff before the death"
        router.kill_replica(0, reason="chaos")     # the prefill replica
        # NEW work arriving after the death must still admit: role
        # purity degrades to shared duty on the survivor, never to a
        # stuck router queue
        reqs += [router.submit(p, gen) for p in prompts[half:]]
        router.drain(max_ticks=400)
        err = check_terminal(reqs) or check_streams(reqs, baseline)
        if err:
            return err
        if any(r.finish_reason not in ("length", "eos") for r in reqs):
            return ("prefill-role death was not transparent: "
                    f"{[r.finish_reason for r in reqs]}")
        st = router.stats()
        if st["replicas_live"] != 1:
            return f"expected 1 live replica: {st}"
        err = check_traces(router.replicas[1].eng)
        if err:
            return err
        fdir = os.path.join(root, "prefill_role_death", "flight")
        return check_flight(fdir, want_reason="router_replica_death")
    scenario("prefill_role_death", prefill_role_death,
             want_flight=False)

    # --- tenant_flood: quota-rejected flood, paying streams exact ---
    def tenant_flood():
        from paddle_tpu.inference.admission import TenantQuota
        rej0 = monitor.counter(
            "serving.admission.rejected.flood").value
        # the flood tenant's bucket covers ONE injected request
        # (cost 3 prompt + 4 gen = 7 tokens); the default (paying)
        # tenant stays unmetered
        router = make_router(
            params, cfg, max_len, replicas=1, family="gpt",
            num_slots=4, concurrent=False,
            admission={"flood": TenantQuota(tokens_per_s=0.5,
                                            burst=7.0)})
        reqs = [router.submit(p, gen) for p in prompts]
        router.drain(max_ticks=400)
        err = check_terminal(reqs) or check_streams(reqs, baseline)
        if err:
            return err
        rej = monitor.counter(
            "serving.admission.rejected.flood").value - rej0
        if rej < 1:
            return f"flood tenant was never quota-rejected (rej={rej})"
        if any(r.finish_reason not in ("length", "eos") for r in reqs):
            return ("the flood touched a paying stream: "
                    f"{[r.finish_reason for r in reqs]}")
        return check_traces(router.replicas[0].eng)
    scenario("tenant_flood", tenant_flood, spec="quota_flood@2:6",
             want_flight=False)

    # --- brownout_ladder: full 0->3->0 on an injected clock ---------
    def brownout_ladder():
        from paddle_tpu.inference.brownout import (BrownoutConfig,
                                                   BrownoutController)

        class _Obj:
            name = "ttft"

        class _SLO:
            pairs = [(3600.0, 60.0)]
            objectives = [_Obj()]
            burn = 0.0

            def burn_rate(self, name, window, now=None):
                return self.burn

        t = [0.0]
        router = make_router(params, cfg, max_len, replicas=1,
                             family="gpt", num_slots=4,
                             concurrent=False, admission={})
        slo = _SLO()
        ctrl = BrownoutController(
            router, slo=slo,
            cfg=BrownoutConfig(breach_ticks=2, recover_ticks=2,
                               cooldown_s=0.0),
            clock=lambda: t[0])
        # two priority classes in flight so level 2 has a victim
        reqs = [router.submit(p, gen, priority=i % 2)
                for i, p in enumerate(prompts)]
        up = []
        slo.burn = 2.0
        for _ in range(8):
            router.step()
            t[0] += 1.0
            if ctrl.tick():
                up.append(ctrl.level)
        if up != [1, 2, 3]:
            return f"escalation trajectory {up}, wanted [1, 2, 3]"
        down = []
        slo.burn = 0.0
        for _ in range(8):
            router.step()
            t[0] += 1.0
            if ctrl.tick():
                down.append(ctrl.level)
        if down != [2, 1, 0]:
            return f"recovery trajectory {down}, wanted [2, 1, 0]"
        router.drain(max_ticks=400)
        # the ladder degrades CAPACITY, never correctness: every
        # stream (including the suspended-and-resumed victims)
        # completes bit-identical
        err = check_terminal(reqs) or check_streams(reqs, baseline)
        if err:
            return err
        if any(r.finish_reason not in ("length", "eos") for r in reqs):
            return ("brownout was not transparent: "
                    f"{[r.finish_reason for r in reqs]}")
        fdir = os.path.join(root, "brownout_ladder", "flight")
        return (check_flight(fdir, want_reason="brownout_escalate")
                or check_flight(fdir, want_reason="brownout_recover"))
    scenario("brownout_ladder", brownout_ladder, want_flight=False)

    # --- process_crash_replay: SIGKILL + journaled recovery ---------
    def process_crash_replay():
        import signal
        import subprocess
        sdir = os.path.join(root, "process_crash_replay")
        jdir = os.path.join(sdir, "journal")
        os.makedirs(jdir, exist_ok=True)
        env = dict(os.environ)
        env.pop(faults.ENV_SPEC, None)
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__),
             "--crash-child", jdir, "--crash-n", str(n_req),
             "--crash-gen", str(gen)],
            capture_output=True, text=True, timeout=600, env=env)
        if proc.returncode != -signal.SIGKILL:
            return (f"child exited {proc.returncode}, wanted SIGKILL "
                    f"(-{signal.SIGKILL}); stderr tail: "
                    f"{proc.stderr[-500:]}")
        replays0 = monitor.counter("serving.journal.replays").value
        router = make_router(params, cfg, max_len, replicas=1,
                             family="gpt", num_slots=4,
                             concurrent=False, journal_dir=jdir)
        if monitor.counter(
                "serving.journal.replays").value == replays0:
            return "recovery replayed nothing (sigkill too late?)"
        streams = {}
        ticks = 0
        while router.has_work() and ticks < 400:
            for req, tok in router.step():
                streams.setdefault(req.id, []).append(int(tok))
            ticks += 1
        j = router.stats()["journal"]
        if j["replayable"] != 0:
            return f"{j['replayable']} requests still un-terminal"
        router.close()
        # the WAL across BOTH processes: every admitted id reaches
        # EXACTLY one terminal event, duplicate-free
        from paddle_tpu.inference.journal import RequestJournal
        wal = RequestJournal(jdir, fsync=False)
        admits, ends = set(), {}
        with open(wal.path, "rb") as f:
            for line in f:
                rec = wal._parse(line.rstrip(b"\n"))
                if rec is None:
                    return "torn record in a cleanly-recovered WAL"
                if rec["ev"] == "admit":
                    admits.add(rec["id"])
                else:
                    ends[rec["id"]] = ends.get(rec["id"], 0) + 1
        wal.close()
        if not admits:
            return "child journaled no admits"
        missing = [i for i in admits if ends.get(i, 0) != 1]
        if missing:
            return (f"admits without exactly one terminal: {missing} "
                    f"(ends={ends})")
        # replayed greedy streams are bit-identical to the fault-free
        # baseline (the child used the drill's own workload)
        for rid, toks in streams.items():
            got = np.asarray(toks, np.int32)
            want = baseline[rid]
            if not np.array_equal(got, want[:len(got)]):
                return (f"replayed stream {rid} diverged: "
                        f"{got.tolist()} vs {want.tolist()}")
        if not streams:
            return "no streams replayed in the parent"
        return None
    scenario("process_crash_replay", process_crash_replay,
             want_flight=False)

    rec.clear()          # don't leak scenario records into the caller's
    #                      process-global ring (in-process test usage)
    dt = time.time() - t_start
    if keep_root:
        _log(f"artifacts kept under {root}")
    if failures:
        _log(f"{len(failures)} FAILURES in {dt:.1f}s:")
        for f in failures:
            _log(f"  - {f}")
        return 1
    _log(f"ALL SCENARIOS PASSED (quick={quick}) in {dt:.1f}s")
    return 0


# ------------------------------------------------------- crash child
def crash_child_main(jdir: str, n_req: int, gen: int) -> int:
    """--crash-child: the sacrificial process of process_crash_replay.
    Builds a JOURNALED router over `jdir`, submits the drill's own
    deterministic workload, and drains under a sigkill fault — the
    process dies mid-decode with no flush and no atexit; the fsynced
    request WAL is all that survives for the parent to recover."""
    from paddle_tpu.inference.router import create_router
    from paddle_tpu.testing import faults
    params, cfg = build_model()
    prompts = build_workload(n_req, 3, 20, cfg.vocab_size)
    # gen+2 ticks in: the first wave is mid-decode (some streams may
    # already be terminal — both replay classes get exercised)
    faults.install(f"sigkill@{gen + 2}",
                   once_dir=os.path.join(jdir, os.pardir, "once"))
    router = create_router(params, cfg, replicas=1, family="gpt",
                           num_slots=4, max_len=64, concurrent=False,
                           journal_dir=jdir)
    for p in prompts:
        router.submit(p, gen)
    router.drain(max_ticks=400)      # SIGKILL fires mid-drain
    _log("crash child survived its own sigkill fault")
    return 3                         # a working drill never gets here


# ------------------------------------------------------------ bench mode
def bench_main(requests=16, gen=32, slots=8, repeats=5) -> int:
    """Measure the guardrail overhead on serving throughput: the same
    workload through an engine with guardrails OFF (PR-4 shape: no
    in-jit isfinite/poison, no watchdog, no deadlines) and ON (the
    default: quarantine guard + watchdog + per-request deadlines that
    never fire). Timed passes ALTERNATE between the two warm engines
    and each side reports its best — on the loaded 1-core build host
    run-to-run noise exceeds the effect, so paired best-of-N is the
    honest estimator. One JSON line — the BASELINE.md "Serving SLO"
    row."""
    from paddle_tpu.models.decode import next_pow2
    from paddle_tpu.models.gpt import GPTConfig, init_gpt_params
    from paddle_tpu.inference.serving import ServingEngine

    hidden, layers, vocab = 128, 2, 512
    cfg = GPTConfig(vocab_size=vocab, hidden_size=hidden,
                    num_layers=layers, num_heads=hidden // 32,
                    max_seq_len=2 * next_pow2(96 + gen),
                    sequence_parallel=False, remat=False,
                    dtype=jnp.float32)
    params = init_gpt_params(cfg, jax.random.PRNGKey(0))
    max_len = next_pow2(96 + gen)
    prompts = build_workload(requests, 8, 96, vocab)
    total = requests * gen

    def build(**kw):
        sub = dict(kw.pop("_submit", {}))
        eng = ServingEngine(params, cfg, family="gpt", num_slots=slots,
                            max_len=max_len, **kw)
        warm = eng.generate(prompts, gen, **sub)     # compile everything
        return eng, sub, warm

    def timed(eng, sub):
        t0 = time.perf_counter()
        outs = eng.generate(prompts, gen, **sub)
        return time.perf_counter() - t0, outs

    eng_off, sub_off, warm_off = build(guardrails=False)
    eng_on, sub_on, warm_on = build(
        guardrails=True, watchdog_timeout=5.0,
        _submit=dict(deadline_s=300.0, deadline_ticks=100_000))
    mismatch = sum(1 for a, b in zip(warm_off, warm_on)
                   if not np.array_equal(a, b))
    best_off = best_on = 1e18
    for _ in range(repeats):
        dt, outs = timed(eng_off, sub_off)
        best_off = min(best_off, dt)
        mismatch += sum(1 for a, b in zip(warm_off, outs)
                        if not np.array_equal(a, b))
        dt, outs = timed(eng_on, sub_on)
        best_on = min(best_on, dt)
        mismatch += sum(1 for a, b in zip(warm_off, outs)
                        if not np.array_equal(a, b))
    tps_off, tps_on = total / best_off, total / best_on
    traces_off, traces_on = eng_off.trace_counts(), eng_on.trace_counts()
    overhead = (tps_off - tps_on) / tps_off * 100.0
    print(json.dumps({
        "metric": "serving_guardrail_overhead",
        "value": round(overhead, 2),
        "unit": "%",
        "backend": jax.devices()[0].platform,
        "tokens_per_sec_guardrails_off": round(tps_off, 1),
        "tokens_per_sec_guardrails_on": round(tps_on, 1),
        "requests": requests, "gen": gen, "slots": slots,
        "repeats": repeats,
        "model": f"{layers}Lx{hidden}d",
        "decode_traces": [traces_off[0], traces_on[0]],
        "stream_mismatches": mismatch,
    }), flush=True)
    return 0 if mismatch == 0 else 1


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--quick", action="store_true",
                    help="smaller workload (CI-sized)")
    ap.add_argument("--bench", action="store_true",
                    help="measure guardrail overhead, print one JSON")
    ap.add_argument("--keep", action="store_true",
                    help="keep scenario artifacts")
    ap.add_argument("--crash-child", metavar="JOURNAL_DIR",
                    help="internal: process_crash_replay's sacrificial "
                         "child (journaled router + sigkill fault)")
    ap.add_argument("--crash-n", type=int, default=6,
                    help="internal: crash-child workload size")
    ap.add_argument("--crash-gen", type=int, default=6,
                    help="internal: crash-child tokens per request")
    args = ap.parse_args()
    if args.crash_child:
        return crash_child_main(args.crash_child, args.crash_n,
                                args.crash_gen)
    if args.bench:
        return bench_main()
    return run_drill(quick=args.quick, keep_root=args.keep)


if __name__ == "__main__":
    sys.exit(main())
