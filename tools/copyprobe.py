"""Verbatim-string probe: flag string literals shared with the reference.

Clean-room gate (CLAUDE.md): no file in this repo may share a verbatim
string literal of >= 25 characters with /root/reference source text.
Extracts every string constant (including f-string fragments) from repo
python files via ast, normalizes whitespace, and substring-searches a
whitespace-normalized read of every reference source file (pure Python,
single pass; each file is also searched with quote-adjacency collapsed
so implicitly-concatenated reference literals still match).

Usage: python tools/copyprobe.py [--min-len 25] [paths...]
Exit 0 = clean, 1 = findings printed.
"""
from __future__ import annotations

import argparse
import ast
import json
import re
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
REFERENCE = Path("/root/reference")

# strings that are forced by the public API or the domain, not authored
# prose: bare op/arg names, dtype lists, URLs, file suffixes — and any
# whitespace-free string (paths, regexes, archive layouts: prose always
# contains spaces, format-forced strings rarely do)
_FORCED = re.compile(
    r"^[\w\.\-/:,\[\] ]*$"  # no sentence-like punctuation at all
)


def _is_forced(s: str) -> bool:
    return " " not in s or bool(_FORCED.match(s))


def _norm(s: str) -> str:
    return re.sub(r"\s+", " ", s).strip()


def _fold(node):
    """Constant-fold string expressions so splitting a copied literal
    (BinOp '+' chains, '/'.join([...])) cannot hide it from the gate."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Add):
        lhs, rhs = _fold(node.left), _fold(node.right)
        if lhs is not None and rhs is not None:
            return lhs + rhs
    if (isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute)
            and node.func.attr == "join" and not node.keywords
            and len(node.args) == 1
            and isinstance(node.args[0], (ast.List, ast.Tuple))):
        sep = _fold(node.func.value)
        parts = [_fold(e) for e in node.args[0].elts]
        if sep is not None and all(p is not None for p in parts):
            return sep.join(parts)
    return None


def harvest(py: Path, min_len: int):
    try:
        tree = ast.parse(py.read_text(errors="ignore"), filename=str(py))
    except SyntaxError:
        return
    for node in ast.walk(tree):
        if isinstance(node, (ast.BinOp, ast.Call, ast.Constant)):
            folded = _fold(node)
            if folded is None:
                continue
            v = _norm(folded)
            if len(v) >= min_len:
                yield v, getattr(node, "lineno", 0)
    # docstring-only files still covered by the walk above


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--min-len", type=int, default=25)
    ap.add_argument("--json", action="store_true")
    ap.add_argument("paths", nargs="*", default=None)
    args = ap.parse_args()

    roots = [Path(p).resolve() for p in args.paths] if args.paths else [
        REPO / "paddle_tpu", REPO / "tools", REPO / "examples"]
    wanted = {}  # normalized string -> [(file, line)]
    for root in roots:
        files = [root] if root.is_file() else sorted(root.rglob("*.py"))
        for py in files:
            try:
                shown = str(py.relative_to(REPO))
            except ValueError:
                shown = str(py)
            for s, line in harvest(py, args.min_len):
                wanted.setdefault(s, []).append((shown, line))
    if not wanted:
        print("no candidate strings")
        return 0

    # docstrings cite reference paths like 'python/paddle/x.py:12' — those
    # literals are citations, not copies; drop pure-path/identifier strings
    probe = {s: w for s, w in wanted.items() if not _is_forced(s)}

    if not probe:
        print("no prose-like strings to probe")
        return 0

    # Normalize every reference source file the same way the candidate
    # strings were normalized, then plain substring-search. One pass per
    # file; C-level str.__contains__ keeps this tractable at 1.5M LoC.
    # A second view collapses close-quote/open-quote adjacency so a repo
    # string that the reference wraps across implicitly-concatenated
    # literals ('"...xx" "yy..."' or '"...xx" f"yy..."') still matches.
    join = re.compile(r"[\"']\s*[frbuFRBU]{0,2}[\"']")
    exts = {".py", ".cc", ".cu", ".h", ".hpp", ".cpp", ".cmake", ".yaml"}
    ref_files = [p for p in REFERENCE.rglob("*")
                 if p.suffix in exts and p.is_file()]
    keys = list(probe)
    where = {}  # string -> [reference files]
    for rf in ref_files:
        try:
            raw = rf.read_text(errors="ignore")
        except OSError:
            continue
        text = _norm(raw)
        joined = _norm(join.sub("", raw))
        for s in keys:
            if s in text or s in joined:
                where.setdefault(s, []).append(
                    str(rf.relative_to(REFERENCE)))
    findings = [{
        "string": s,
        "repo": probe[s],
        "reference_files": sorted(where[s])[:5],
    } for s in keys if s in where]
    if args.json:
        print(json.dumps(findings, indent=1))
    else:
        for f in findings:
            print(f"SHARED ({len(f['string'])} ch): {f['string'][:100]!r}")
            for loc in f["repo"][:3]:
                print(f"  repo: {loc[0]}:{loc[1]}")
            for rf in f["reference_files"][:3]:
                print(f"  ref:  {rf}")
        print(f"\n{len(findings)} shared strings "
              f"({len(probe)} probed, {len(wanted) - len(probe)} skipped "
              "as identifier-only)")
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
