#!/usr/bin/env python
"""Compiled-memory regression gate: peak HBM per canonical plan.

The memory sibling of tools/audit_gate.py (which pins resharding
finding counts): this gate re-lowers the canonical train plans AND the
canonical serving layouts on the CPU mesh, reads XLA's compiled memory
accounting through profiler/mem_audit.py, and diffs each plan's
`peak_bytes` against the stored baseline (perf/mem_baseline.json):

- compiled peak GREW beyond --tolerance vs the stored peak  -> FAIL
- a plan the baseline does not list                          -> pass
  (with a note to --write-baseline and start pinning it)
- peak SHRANK beyond tolerance                               -> pass
  (with a note to --write-baseline and bank the win)

ONE exit code. Wired into `tools/chaos_drill.py --gate` (the
pre-commit robustness gate) so an HBM regression — a dropped donation,
a doubled buffer, a remat policy that silently rematerializes nothing
— is caught at commit time, before it becomes a mystery OOM at scale.

Usage:
  python tools/mem_gate.py                   # gate vs stored baseline
  python tools/mem_gate.py --write-baseline  # re-pin after a win
  python tools/mem_gate.py --plans fsdp8 --json
"""
import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)
TOOLS = os.path.dirname(os.path.abspath(__file__))
if TOOLS not in sys.path:
    sys.path.insert(0, TOOLS)

BASELINE_PATH = os.path.join(REPO, "perf", "mem_baseline.json")
# the same canonical train plans audit_gate pins, plus the two serving
# layouts serving_attrib A/Bs (BASELINE.md §Memory observability)
CANONICAL_TRAIN = ("dp2_fsdp2_tp2", "fsdp8", "dp2_tp2_pp2_mb4")
CANONICAL_SERVING = ("dense_fp", "paged_int8")
CANONICAL_PLANS = CANONICAL_TRAIN + CANONICAL_SERVING
TOLERANCE = 0.05


def measure_train_plan(name: str) -> dict:
    """Compiled peak for ONE canonical train plan on the small
    observability config — the same cfg/batch/seq audit_gate and
    train_attrib lower, so every gate describes the same executable."""
    import train_attrib

    from paddle_tpu.models.gpt import PARAM_SPECS
    from paddle_tpu.parallel.planner import plan_train
    from paddle_tpu.profiler import mem_audit

    class _Args:
        vocab, hidden, layers, seq = 512, 128, 2, 32

    cfg = train_attrib.build_cfg(_Args)
    deg = train_attrib.parse_plan_name(name)
    n_devices = deg["dp"] * deg["fsdp"] * deg["tp"] * deg.get("pp", 1)
    plan = plan_train(cfg, n_devices, 8, param_specs=PARAM_SPECS, **deg)
    res = mem_audit.audit_train_memory(cfg, plan, 8, seq=_Args.seq)
    return {"peak_bytes": int(res["compiled"].get("peak_bytes", 0)),
            "ledger_bytes": int(res["ledger"]["total"]),
            "gap_fraction": res["gap_fraction"],
            "findings": sorted(f["kind"] for f in res["findings"])}


def measure_serving_layout(name: str) -> dict:
    """Compiled decode-tick peak for ONE canonical serving layout on
    the chaos-drill-sized model (dense_fp | paged_int8)."""
    import jax

    from paddle_tpu.inference.serving import ServingEngine
    from paddle_tpu.models.gpt import GPTConfig, init_gpt_params
    from paddle_tpu.profiler import mem_audit

    cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=2,
                    num_heads=2, max_seq_len=64, dtype="float32")
    params = init_gpt_params(cfg, jax.random.PRNGKey(0))
    kw = ({} if name == "dense_fp"
          else {"kv_layout": "paged", "page_size": 8, "quant": "int8"})
    eng = ServingEngine(params, cfg, family="gpt", num_slots=3,
                        max_len=64, **kw)
    res = mem_audit.audit_serving_memory(eng)
    comps = res["ledger"]["components"]
    return {"peak_bytes": int(res["compiled"].get("peak_bytes", 0)),
            "ledger_bytes": int(res["ledger"]["total"]),
            # the split KV rows: device HBM (inside ledger_bytes) vs
            # the host tier (host RAM, outside it) — pinned so a
            # regression that silently re-prices spilled pages as
            # device-resident fails the gate
            "kv_device_bytes": int(comps["kv_pool_device"]),
            "kv_host_bytes": int(comps["kv_pool_host"]),
            "gap_fraction": res["gap_fraction"],
            "findings": sorted(f["kind"] for f in res["findings"])}


def measure(name: str) -> dict:
    if name in CANONICAL_SERVING:
        return measure_serving_layout(name)
    return measure_train_plan(name)


def gate(plans, baseline_path: str, tolerance: float,
         write: bool = False, as_json: bool = False) -> int:
    stored = {}
    if os.path.exists(baseline_path):
        with open(baseline_path) as f:
            stored = json.load(f)
    base_plans = stored.get("plans", {})
    observed, regressions, shrunk, unpinned = {}, [], [], []
    for name in plans:
        row = measure(name)
        observed[name] = row
        base = base_plans.get(name, {}).get("peak_bytes")
        if base is None:
            unpinned.append(name)
            continue
        base = int(base)
        seen = row["peak_bytes"]
        if base > 0 and seen > base * (1.0 + tolerance):
            regressions.append((name, base, seen))
        elif base > 0 and seen < base * (1.0 - tolerance):
            shrunk.append((name, base, seen))
    if write:
        doc = {
            "comment": "Compiled peak-HBM baseline per canonical plan "
                       "(tools/mem_gate.py --write-baseline). The gate "
                       "fails when a plan's compiled peak grows beyond "
                       "the tolerance.",
            "tolerance": tolerance,
            "plans": {n: {k: r[k] for k in
                          ("peak_bytes", "ledger_bytes",
                           "kv_device_bytes", "kv_host_bytes")
                          if k in r}
                      for n, r in observed.items()},
        }
        os.makedirs(os.path.dirname(baseline_path), exist_ok=True)
        with open(baseline_path, "w") as f:
            json.dump(doc, f, indent=2, sort_keys=True)
            f.write("\n")
        print(f"[mem-gate] baseline written: {baseline_path}",
              flush=True)
        return 0
    if as_json:
        print(json.dumps({"metric": "mem_gate", "observed": observed,
                          "regressions": [
                              {"plan": p, "baseline": b, "seen": s}
                              for p, b, s in regressions]}),
              flush=True)
    for p, b, s in regressions:
        print(f"[mem-gate] REGRESSION {p}: compiled peak "
              f"{b / 1e6:.2f} -> {s / 1e6:.2f} MB "
              f"(+{(s - b) / b:.1%} > {tolerance:.0%})", flush=True)
    if regressions:
        print(f"[mem-gate] MEMORY GATE RED ({len(regressions)} "
              "plan(s) grew)", flush=True)
        return 1
    for p in unpinned:
        print(f"[mem-gate] {p}: not in baseline — pin it with "
              "--write-baseline", flush=True)
    for p, b, s in shrunk:
        print(f"[mem-gate] {p}: compiled peak {b / 1e6:.2f} -> "
              f"{s / 1e6:.2f} MB — bank it with --write-baseline",
              flush=True)
    print(f"[mem-gate] GREEN: {len(observed)} plan(s) within "
          f"{tolerance:.0%} of baseline", flush=True)
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--plans", default=",".join(CANONICAL_PLANS),
                    help="comma-separated plan/layout names to measure")
    ap.add_argument("--baseline", default=BASELINE_PATH)
    ap.add_argument("--tolerance", type=float, default=None,
                    help="allowed peak growth fraction (default: the "
                         "baseline's stored tolerance, else "
                         f"{TOLERANCE})")
    ap.add_argument("--write-baseline", action="store_true",
                    help="re-pin the stored baseline from this run")
    ap.add_argument("--json", action="store_true")
    args = ap.parse_args(argv)

    from paddle_tpu.device import pin_cpu
    if not pin_cpu(8):
        print("[mem-gate] could not pin the 8-device CPU platform",
              flush=True)
        return 2
    tolerance = args.tolerance
    if tolerance is None:
        tolerance = TOLERANCE
        if os.path.exists(args.baseline):
            with open(args.baseline) as f:
                tolerance = float(json.load(f).get("tolerance",
                                                   TOLERANCE))
    plans = [p for p in args.plans.split(",") if p]
    return gate(plans, args.baseline, tolerance,
                write=args.write_baseline, as_json=args.json)


if __name__ == "__main__":
    sys.exit(main())
