"""Ledger-vs-compiled HBM attribution per canonical plan.

The memory sibling of tools/train_attrib.py / serving_attrib.py:
instead of joining measured ms against the FLOPs roofline, this joins
the analytical memory ledger (cost_model.train_memory_ledger /
serving_memory_ledger — the SAME formula the planner's HBM gate
consumes) against XLA's compiled memory accounting for the executable
that actually lowers (profiler/mem_audit.py), one row per plan:

- train rows: the canonical observability plans (dp2_fsdp2_tp2, fsdp8,
  dp2_tp2_pp2_mb4) on the 8-virtual-device CPU mesh, plus the 6.7B
  AOT lowering (--x67b: the tests/test_67b_lowering.py config on a
  64-virtual-device mesh, subprocess-isolated like the test);
- serving rows: the dense_fp vs paged_int8 layouts of the chaos-drill
  model (the serving_attrib A/B pair), audited through the live
  engine's own decode tick.

Each row names the ledger components, the compiled temp/argument/
output/alias split, the relative gap, and any hbm_underestimate /
hbm_overestimate findings — the evidence table BASELINE.md §Memory
observability publishes and tools/mem_gate.py pins.

Usage:
  python tools/mem_attrib.py --pretty              # all canonical rows
  python tools/mem_attrib.py --plans fsdp8 --json
  python tools/mem_attrib.py --x67b                # add the 6.7B row
"""
from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)
TOOLS = os.path.dirname(os.path.abspath(__file__))
if TOOLS not in sys.path:
    sys.path.insert(0, TOOLS)

# CPU unconditionally in script mode (the axon-tunnel trap, CLAUDE.md);
# the 6.7B worker re-pins 64 virtual devices in its own process
from paddle_tpu.device import pin_cpu            # noqa: E402
if __name__ == "__main__" and "--tpu" not in sys.argv:
    pin_cpu(64 if "--_x67b-worker" in sys.argv else 8)

CANONICAL_TRAIN = ("dp2_fsdp2_tp2", "fsdp8", "dp2_tp2_pp2_mb4")
CANONICAL_SERVING = ("dense_fp", "paged_int8")
TOLERANCE = 0.5


def _log(msg):
    print(f"[mem_attrib] {msg}", file=sys.stderr, flush=True)


def attrib_row(res: dict) -> dict:
    """One audit result -> the mem_attrib row (the train_attrib row
    format, memory flavored). Importable so recorded docs re-join
    offline (tests/test_mem_observability.py)."""
    led, comp = res["ledger"], res["compiled"]
    return {
        "plan": res["plan"],
        "ledger_bytes": round(led["total"]),
        "components": {k: round(v)
                       for k, v in led["components"].items()},
        "compiled_peak_bytes": comp.get("peak_bytes"),
        "compiled": {k: v for k, v in comp.items()
                     if k != "peak_bytes"},
        "gap_fraction": res["gap_fraction"],
        "findings": res["findings"],
    }


def measure_train_plan(name: str, tolerance: float = TOLERANCE) -> dict:
    """Audit ONE canonical train plan on the small observability
    config — the same cfg/batch/seq train_attrib and audit_gate lower,
    so every evidence table describes the same executable."""
    import train_attrib

    from paddle_tpu.models.gpt import PARAM_SPECS
    from paddle_tpu.parallel.planner import plan_train
    from paddle_tpu.profiler import mem_audit

    class _Args:
        vocab, hidden, layers, seq = 512, 128, 2, 32

    cfg = train_attrib.build_cfg(_Args)
    deg = train_attrib.parse_plan_name(name)
    n_devices = deg["dp"] * deg["fsdp"] * deg["tp"] * deg.get("pp", 1)
    plan = plan_train(cfg, n_devices, 8, param_specs=PARAM_SPECS, **deg)
    return attrib_row(mem_audit.audit_train_memory(
        cfg, plan, 8, seq=_Args.seq, tolerance=tolerance))


def measure_serving_layout(name: str,
                           tolerance: float = TOLERANCE) -> dict:
    """Audit ONE canonical serving layout (dense_fp | paged_int8) on
    the chaos-drill model through the live engine's decode tick."""
    import jax

    from paddle_tpu.inference.serving import ServingEngine
    from paddle_tpu.models.gpt import GPTConfig, init_gpt_params
    from paddle_tpu.profiler import mem_audit

    cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=2,
                    num_heads=2, max_seq_len=64, dtype="float32")
    params = init_gpt_params(cfg, jax.random.PRNGKey(0))
    kw = ({} if name == "dense_fp"
          else {"kv_layout": "paged", "page_size": 8, "quant": "int8"})
    eng = ServingEngine(params, cfg, family="gpt", num_slots=3,
                        max_len=64, **kw)
    return attrib_row(mem_audit.audit_serving_memory(
        eng, tolerance=tolerance))


def x67b_row_inproc(tolerance: float = TOLERANCE) -> dict:
    """The 6.7B AOT row (worker process: 64 virtual CPU devices
    already pinned). tests/test_67b_lowering.py's exact config/plan —
    abstract avals only, no 6.7B params materialize."""
    import jax.numpy as jnp

    from paddle_tpu.models.gpt import GPTConfig, PARAM_SPECS
    from paddle_tpu.parallel.planner import plan_train
    from paddle_tpu.profiler import mem_audit

    cfg = GPTConfig(vocab_size=50304, hidden_size=4096, num_layers=32,
                    num_heads=32, max_seq_len=2048, dtype=jnp.bfloat16,
                    remat="dots", sequence_parallel=True)
    plan = plan_train(cfg, 64, 16, dp=2, fsdp=2, tp=4, pp=4,
                      microbatches=4, param_specs=PARAM_SPECS)
    return attrib_row(mem_audit.audit_train_memory(
        cfg, plan, 16, seq=2048, tolerance=tolerance))


def x67b_row(tolerance: float = TOLERANCE, timeout: int = 900) -> dict:
    """Run the 6.7B lowering in a subprocess (its 64-device pin and
    multi-minute GSPMD compile must not contaminate this process)."""
    res = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--_x67b-worker",
         "--tolerance", str(tolerance)],
        cwd=REPO, capture_output=True, text=True, timeout=timeout)
    if res.returncode != 0:
        raise RuntimeError(f"6.7B worker failed (rc={res.returncode}): "
                           f"{res.stderr[-2000:]}")
    return json.loads(res.stdout.strip().splitlines()[-1])


def render_table(rows) -> str:
    """The human-readable ledger-vs-compiled table."""
    lines = []
    hdr = (f"{'plan':<18} {'ledger MB':>10} {'compiled MB':>12} "
           f"{'gap':>7} {'findings':>22}  top components")
    lines.append(hdr)
    lines.append("-" * len(hdr))
    for r in rows:
        total = max(r["ledger_bytes"], 1)
        comps = "  ".join(
            f"{k}={v / 1e6:.2f}M"
            for k, v in sorted(r["components"].items(),
                               key=lambda kv: -kv[1])
            if v / total >= 0.02)
        peak = r["compiled_peak_bytes"]
        gap = r["gap_fraction"]
        kinds = ",".join(sorted({f["kind"] for f in r["findings"]})) \
            or "-"
        lines.append(
            f"{r['plan']:<18} {r['ledger_bytes'] / 1e6:>10.2f} "
            f"{(peak or 0) / 1e6:>12.2f} "
            f"{gap if gap is not None else float('nan'):>+7.0%} "
            f"{kinds:>22}  {comps}")
    return "\n".join(lines)


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--plans",
                    default=",".join(CANONICAL_TRAIN
                                     + CANONICAL_SERVING),
                    help="comma-separated plan/layout names")
    ap.add_argument("--tolerance", type=float, default=TOLERANCE,
                    help="relative gap beyond which a finding is named")
    ap.add_argument("--x67b", action="store_true",
                    help="add the 6.7B AOT lowering row (subprocess, "
                         "64 virtual devices, minutes of compile)")
    ap.add_argument("--_x67b-worker", action="store_true",
                    dest="x67b_worker", help=argparse.SUPPRESS)
    ap.add_argument("--tpu", action="store_true",
                    help="run on the default (TPU) backend")
    ap.add_argument("--pretty", action="store_true")
    args = ap.parse_args()

    if args.x67b_worker:
        print(json.dumps(x67b_row_inproc(args.tolerance)), flush=True)
        return 0

    rows = []
    for name in [n for n in args.plans.split(",") if n]:
        _log(f"auditing {name} ...")
        if name in CANONICAL_SERVING:
            rows.append(measure_serving_layout(name, args.tolerance))
        else:
            rows.append(measure_train_plan(name, args.tolerance))
    if args.x67b:
        _log("auditing 6.7B AOT lowering (subprocess) ...")
        rows.append(x67b_row(args.tolerance))
    import jax
    doc = {"metric": "mem_attribution",
           "backend": jax.devices()[0].platform,
           "tolerance": args.tolerance, "plans": rows}
    print(json.dumps(doc), flush=True)
    if args.pretty:
        print(render_table(rows), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
