"""Diff pytest failure sets: are any failures NEW vs the baseline?

Automates the ROADMAP tier-1 ritual ("always diff FAILED lists against
a clean-HEAD worktree" — this container carries ~46 pre-existing
environment failures, so raw counts mean nothing; the SET is the
signal). Parses `FAILED`/`ERROR` node ids out of pytest logs (the -q
summary lines, trailing ` - reason` stripped) and compares:

  python tools/diff_failures.py NEW.log                # vs the stored
                                                       # baseline file
  python tools/diff_failures.py NEW.log OLD.log        # log vs log
  python tools/diff_failures.py --write-baseline \\
      tests/baseline_failures_tier1.txt NEW.log        # (re)store

Exit status: 0 when no NEW failures (fixed/removed ones are reported
but never fail the gate), 1 when any test fails that the baseline did
not, 2 on usage/IO errors. The stored baseline
(tests/baseline_failures_tier1.txt) is one node id per line, '#'
comments ignored — regenerate it whenever the environment set moves
(and say so in ROADMAP's re-anchor note).
"""
from __future__ import annotations

import argparse
import os
import re
import sys

DEFAULT_BASELINE = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "tests", "baseline_failures_tier1.txt")

_LINE_RE = re.compile(r"^(?:FAILED|ERROR)\s+(\S+)")


def parse_log(path: str) -> set:
    """FAILED/ERROR node ids from a pytest log (short summary lines)."""
    out = set()
    with open(path, errors="replace") as f:
        for line in f:
            m = _LINE_RE.match(line.strip())
            if m:
                out.add(m.group(1).rstrip(":"))
    return out


def parse_baseline(path: str) -> set:
    """Node ids from a stored baseline file OR a pytest log. A file
    containing any FAILED/ERROR summary lines is a log and parses
    exactly like new_log; otherwise it's id-per-line, where only
    tokens that look like pytest node ids ('::'-qualified, or a bare
    collection-error file ending in .py) are accepted — stray prose in
    a hand-edited baseline must not pollute the set (or mask a real
    new failure by collision)."""
    log_ids = parse_log(path)
    if log_ids:
        return log_ids
    ids = set()
    with open(path, errors="replace") as f:
        for line in f:
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            tok = line.split()[0]
            if "::" in tok or tok.endswith(".py"):
                ids.add(tok)
    return ids


def diff(new: set, old: set) -> dict:
    return {"added": sorted(new - old), "removed": sorted(old - new),
            "unchanged": len(new & old)}


def write_baseline(path: str, ids: set, source: str) -> None:
    import datetime
    tmp = f"{path}.tmp{os.getpid()}"
    now = datetime.date.today().isoformat()
    with open(tmp, "w") as f:
        f.write("# Tier-1 pre-existing failure baseline (ROADMAP "
                "tier-1 verify command).\n"
                "# One pytest node id per line; '#' comments "
                "ignored.\n"
                "# Regenerate: python tools/diff_failures.py "
                "--write-baseline tests/baseline_failures_tier1.txt "
                "<tier1.log>\n"
                f"# Captured {now} from {source}.\n")
        for nid in sorted(ids):
            f.write(nid + "\n")
    os.replace(tmp, path)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("new_log", help="pytest log of the tree under test")
    ap.add_argument("old", nargs="?", default=DEFAULT_BASELINE,
                    help="baseline: a stored id-per-line file or a "
                         "second pytest log (default: "
                         "tests/baseline_failures_tier1.txt)")
    ap.add_argument("--write-baseline", metavar="PATH", default=None,
                    help="store new_log's failure set as the baseline "
                         "file at PATH and exit 0")
    args = ap.parse_args(argv)
    try:
        new = parse_log(args.new_log)
    except OSError as e:
        print(f"cannot read {args.new_log}: {e}", file=sys.stderr)
        return 2
    if args.write_baseline:
        write_baseline(args.write_baseline, new, args.new_log)
        print(f"wrote {len(new)} ids to {args.write_baseline}")
        return 0
    try:
        old = parse_baseline(args.old)
    except OSError as e:
        print(f"cannot read baseline {args.old}: {e}", file=sys.stderr)
        return 2
    d = diff(new, old)
    print(f"failures: {len(new)} now / {len(old)} baseline "
          f"({d['unchanged']} shared)")
    for nid in d["removed"]:
        print(f"  FIXED   {nid}")
    for nid in d["added"]:
        print(f"  NEW     {nid}")
    if d["added"]:
        print(f"{len(d['added'])} NEW failure(s) vs baseline",
              file=sys.stderr)
        return 1
    print("no new failures")
    return 0


if __name__ == "__main__":
    sys.exit(main())
