"""Fused step-kernel A/B bench + the evidence-gated registry writer.

The two PR 16 Pallas step kernels ship OFF by default; this tool is the
ONLY path that turns them on (ISSUE 16 "adoption only via the
evidence-gated writer"):

- `ce`: two-pass `pallas_ce.ce_with_logits` (fwd kernel + bwd kernel)
  vs the one-pass `pallas_ce.ce_fused_train` (d_logits produced in the
  forward launch; backward is an elementwise scale) at the flagship
  head shape — adopt writes `ce -> pallas_fused`;
- `fused_update`: the tree-level `models.gpt.apply_adamw` oracle vs
  `pallas_update.fused_apply_adamw` (one launch per leaf, f32 master
  math in VMEM) over a model-scaled param tree — adopt writes
  `fused_update -> pallas`.

Each row is kernel-registry evidence format (ms + flops/bytes_moved +
knobs); `--adopt` persists a winner through `registry.adopt`, which
re-runs the roofline plausibility gate — a tunnel-artifact timing
cannot become the shipped default. Parity versus the jax oracle is
checked IN-RUN before any timing counts; a parity miss refuses
adoption no matter the speedup.

On CPU (default; the 8-virtual-device pin is unconditional) the Pallas
legs run in interpret mode: parity is meaningful, timings are not —
adoption is refused outside TPU-class backends. Usage:

  python tools/bench_fused_step.py            # CPU parity + oracle rows
  python tools/bench_fused_step.py --tpu      # chip A/B rows
  python tools/bench_fused_step.py --tpu --adopt
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

HERE = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, HERE)
sys.path.insert(1, os.path.dirname(os.path.abspath(__file__)))

# adoption refused below this measured speedup of fused over the
# incumbent (same bar as the serving writers: a within-noise "win"
# must not flip the default)
MIN_SPEEDUP = 1.03


def log(m):
    print(f"[fused-step] {m}", file=sys.stderr, flush=True)


def emit(rec):
    print(json.dumps(rec), flush=True)
    return rec


def bench_ce(T, V, iters, interpret):
    """CE value+grad A/B: two-pass kernel pair vs one-pass fused.
    The chained carry is a gradient-descent-on-logits loop, so every
    scan iteration pays exactly one fwd+bwd of the measured impl."""
    import jax
    import jax.numpy as jnp
    from bench_util import chained_ms
    from paddle_tpu.kernels import pallas_ce

    dtype = jnp.bfloat16
    x = jax.random.normal(jax.random.PRNGKey(0), (T, V), dtype)
    tgt = jax.random.randint(jax.random.PRNGKey(1), (T,), 0, V,
                             jnp.int32)

    def sgd_step(ce_fn):
        def loss(xx):
            return jnp.mean(ce_fn(xx, tgt, interpret=interpret))
        g = jax.grad(loss)
        return lambda xx: (xx - 1e-3 * g(xx)).astype(dtype)

    # parity first: fused value+grad vs the f32 jax oracle
    def oracle(xx):
        lf = xx.astype(jnp.float32)
        lse = jax.scipy.special.logsumexp(lf, axis=-1)
        return jnp.mean(lse - jnp.take_along_axis(
            lf, tgt[:, None], -1)[:, 0])

    want_l, want_g = jax.value_and_grad(oracle)(x)
    got_l, got_g = jax.value_and_grad(lambda xx: jnp.mean(
        pallas_ce.ce_fused_train(xx, tgt, interpret=interpret)))(x)
    err = max(float(jnp.abs(want_l - got_l)),
              float(jnp.max(jnp.abs(want_g.astype(jnp.float32)
                                    - got_g.astype(jnp.float32)))))
    parity_ok = err < 2e-2        # bf16 logits; grads are O(1/V)
    log(f"ce parity max_abs_err={err:.2e} ok={parity_ok}")

    length = 4 if interpret else 32
    ms_two = chained_ms(sgd_step(pallas_ce.ce_with_logits), x,
                        length=length, iters=iters)
    ms_fused = chained_ms(sgd_step(pallas_ce.ce_fused_train), x,
                          length=length, iters=iters)
    nb = x.dtype.itemsize
    # one application = fwd logits stream + dx produce/consume
    bytes_moved = 3.0 * T * V * nb
    common = {"flops": 0.0, "bytes_moved": bytes_moved,
              "knobs": {"T": T, "V": V, "dtype": "bf16",
                        "interpret": interpret},
              "parity_max_abs_err": round(err, 6)}
    emit({"variant": "ce_two_pass", "ms": round(ms_two, 3), **common})
    emit({"variant": "ce_fused", "ms": round(ms_fused, 3), **common})
    return {"kernel": "ce", "impl": "pallas_fused",
            "ms": ms_fused, "ms_incumbent": ms_two,
            "bytes_moved": bytes_moved, "flops": 0.0,
            "parity_ok": parity_ok}


def bench_update(n_rows, iters, interpret):
    """AdamW master-update A/B over a model-scaled tree: the jax
    tree-level oracle vs the fused per-leaf kernel. The chained carry
    is (params, m, v) under a fixed grad — each iteration is exactly
    one full optimizer application."""
    import jax
    import jax.numpy as jnp
    from bench_util import force
    from paddle_tpu.kernels import pallas_update
    from paddle_tpu.models.gpt import apply_adamw

    ks = jax.random.split(jax.random.PRNGKey(2), 3)
    params = {f"w{i}": jax.random.normal(k, (n_rows, 1024),
                                         jnp.float32) * 0.02
              for i, k in enumerate(ks)}
    grads = {k: jnp.full_like(v, 1e-4) for k, v in params.items()}
    opt = {"m": {k: jnp.zeros_like(v) for k, v in params.items()},
           "v": {k: jnp.zeros_like(v) for k, v in params.items()},
           "step": jnp.zeros((), jnp.float32)}

    # parity first (the dedicated interpret tests pin this rule for
    # rule; here is the in-run gate adoption depends on). The jax legs
    # pin the oracle path explicitly: after a successful --adopt,
    # apply_adamw itself would route to the fused kernel and the A/B
    # would compare the kernel with itself.
    os.environ["PADDLE_TPU_DISABLE_PALLAS_UPDATE"] = "1"
    want = apply_adamw(grads, params, opt, 1e-3)
    os.environ.pop("PADDLE_TPU_DISABLE_PALLAS_UPDATE", None)
    got = pallas_update.fused_apply_adamw(grads, params, opt, 1e-3,
                                          interpret=interpret)
    err = max(float(jnp.max(jnp.abs(a - b)))
              for a, b in zip(jax.tree_util.tree_leaves(want[:2]),
                              jax.tree_util.tree_leaves(got[:2])))
    parity_ok = err < 1e-5
    log(f"update parity max_abs_err={err:.2e} ok={parity_ok}")

    length = 2 if interpret else 32

    def run(update_fn):
        fused = update_fn is pallas_update.fused_apply_adamw
        kw = {"interpret": interpret} if fused else {}
        if not fused:
            os.environ["PADDLE_TPU_DISABLE_PALLAS_UPDATE"] = "1"

        @jax.jit
        def chained(params, opt):
            def body(carry, _):
                p, o = carry
                p, o = update_fn(grads, p, o, 1e-3, **kw)
                return (p, o), None
            (p, o), _ = jax.lax.scan(body, (params, opt), None,
                                     length=length)
            return p, o
        try:
            force(chained(params, opt))
            t0 = time.perf_counter()
            out = chained(params, opt)
            force(out)
            return (time.perf_counter() - t0) / length * 1e3
        finally:
            os.environ.pop("PADDLE_TPU_DISABLE_PALLAS_UPDATE", None)

    ms_jax = min(run(apply_adamw) for _ in range(iters))
    ms_fused = min(run(pallas_update.fused_apply_adamw)
                   for _ in range(iters))
    n_params = sum(int(v.size) for v in params.values())
    # p rw + m rw + v rw + g read, all f32 master math
    bytes_moved = 7.0 * n_params * 4
    common = {"flops": 0.0, "bytes_moved": bytes_moved,
              "knobs": {"n_params": n_params, "interpret": interpret},
              "parity_max_abs_err": round(err, 9)}
    emit({"variant": "adamw_jax", "ms": round(ms_jax, 3), **common})
    emit({"variant": "adamw_fused", "ms": round(ms_fused, 3), **common})
    return {"kernel": "fused_update", "impl": "pallas",
            "ms": ms_fused, "ms_incumbent": ms_jax,
            "bytes_moved": bytes_moved, "flops": 0.0,
            "parity_ok": parity_ok}


def maybe_adopt(res, window: str) -> None:
    from paddle_tpu.kernels import registry
    import jax
    doc = {"metric": "fused_step_adopt", "kernel": res["kernel"],
           "impl": res["impl"]}
    speedup = (res["ms_incumbent"] / res["ms"]
               if res["ms"] > 0 else 0.0)
    doc["speedup"] = round(speedup, 3)
    if registry.backend_class(jax.default_backend()) != "tpu":
        doc["adopt"] = "refused: not a TPU-class backend"
    elif not res["parity_ok"]:
        doc["adopt"] = "refused: parity gate failed"
    elif speedup < MIN_SPEEDUP:
        doc["adopt"] = (f"refused: speedup {speedup:.3f}x < "
                        f"{MIN_SPEEDUP}x over incumbent")
    else:
        problem = registry.adopt(
            res["kernel"], res["impl"], res["ms"],
            flops=res["flops"], bytes_moved=res["bytes_moved"],
            backend="tpu", source="tools/bench_fused_step.py",
            window=window)
        doc["adopt"] = problem or "adopted"
    emit(doc)


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--tpu", action="store_true",
                    help="run on the default (TPU) backend; otherwise "
                         "pin CPU and run Pallas legs in interpret mode")
    ap.add_argument("--adopt", action="store_true",
                    help="persist winners through registry.adopt "
                         "(TPU-class backends only)")
    ap.add_argument("--ce-shape", default="8192x32768",
                    help="TxV for the CE rows (flagship head shape)")
    ap.add_argument("--rows", type=int, default=4096,
                    help="rows per [rows,1024] f32 leaf, 3 leaves")
    ap.add_argument("--iters", type=int, default=3)
    ap.add_argument("--window", default="")
    args = ap.parse_args()

    if not args.tpu:
        from paddle_tpu.device import pin_cpu
        if not pin_cpu(8):
            log("could not pin the CPU platform")
            return 17
    import jax
    platform = jax.devices()[0].platform
    interpret = platform not in ("tpu", "axon")
    log(f"backend {platform} interpret={interpret}")
    if args.tpu and interpret:
        log("wanted TPU, got CPU; abandoning")
        return 17

    T, V = (int(v) for v in args.ce_shape.split("x"))
    if interpret:
        # interpret-mode walls are minutes/MB — shrink to parity-scale
        T, V, rows = 256, 2048, 512
    else:
        rows = args.rows
    results = [bench_ce(T, V, args.iters, interpret),
               bench_update(rows, args.iters, interpret)]
    if args.adopt:
        for res in results:
            maybe_adopt(res, args.window)
    return 0


if __name__ == "__main__":
    sys.exit(main())
