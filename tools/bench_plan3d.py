"""plan3d rung: the planner-driven dp×fsdp×tp sharded train step, timed.

The measurement half of ROADMAP item 5's "claim the next best_tpu MFU
high-water mark": run `parallel.planner.plan_train`'s chosen (or an
explicitly requested) 3D assignment end to end — GSPMD train step with
pinned shardings, donation on — and report steady-state ms/step,
tokens/s and MFU in the MULTICHIP-format JSON the driver artifacts use
({"n_devices", "rc", "ok", "skipped", "tail", ...} — one line per leg).

Robustness follows bench.py: the orchestrator runs each leg in a fresh
subprocess under a hard timeout. The CPU leg pins the 8-virtual-device
platform UNCONDITIONALLY (CLAUDE.md: never gate the pin on the env) so
it runs with the tunnel dead; the TPU leg is attempted only with --tpu
AND a live tunnel probe (bench._probe_tpu — short first timeout,
PADDLE_TPU_SKIP_TPU_PROBE honored), and is marked "skipped" otherwise.

Usage:
  python tools/bench_plan3d.py            # CPU 8-virtual-device leg
  python tools/bench_plan3d.py --tpu      # + TPU leg when tunnel is up
  python tools/bench_plan3d.py --run cpu8 # one leg, in-process (driver)
"""
from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

HERE = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, HERE)


def log(m):
    print(f"[plan3d] {m}", file=sys.stderr, flush=True)


# leg -> (want_tpu, n_devices (0 = all), model kw, batch, seq, iters,
#         timeout_s, explicit degrees or None). CPU shapes follow the
# bench.py cpu rung scaled to the 8-device mesh and PIN the canonical
# dp2×fsdp2×tp2 layout (the cost model would rightly pick pure dp for
# shapes this small — the rung's job is to exercise the 3D path); the
# TPU leg uses the flagship bench shapes with the SEARCHED plan so its
# MFU is comparable with BENCH_window best_tpu rows.
LEGS = {
    "cpu8": (False, 8, dict(vocab_size=512, hidden_size=128, num_layers=2,
                            num_heads=4, max_seq_len=128, remat=False,
                            dtype="float32"), 8, 64, 3, 600,
             dict(dp=2, fsdp=2, tp=2)),
    # the 4D rung (tpu_campaign --plan4d): the full-manual pipelined
    # step on a pinned dp2×tp2×pp2 grid, microbatches = 2·pp — reports
    # bubble_fraction next to ms/step (ISSUE 15)
    "cpu8_pp": (False, 8, dict(vocab_size=512, hidden_size=128,
                               num_layers=2, num_heads=4,
                               max_seq_len=128, remat=False,
                               dtype="float32"), 8, 64, 3, 600,
                dict(dp=2, fsdp=1, tp=2, pp=2, microbatches=4)),
    # overlap A/B legs (ISSUE 16): the SAME grids with the latency-
    # hiding collective schedule on (plan_train(..., overlap=True) —
    # double-buffered ZeRO-3 gather on pp plans, XLA async-collective/
    # collective-matmul flags on the GSPMD path; TPU-only there, so the
    # cpu8 A/B pins parity + trace count while the tpu A/B measures)
    "cpu8_overlap": (False, 8,
                     dict(vocab_size=512, hidden_size=128, num_layers=2,
                          num_heads=4, max_seq_len=128, remat=False,
                          dtype="float32"), 8, 64, 3, 600,
                     dict(dp=2, fsdp=2, tp=2, overlap=True)),
    "cpu8_pp_overlap": (False, 8,
                        dict(vocab_size=512, hidden_size=128,
                             num_layers=2, num_heads=4, max_seq_len=128,
                             remat=False, dtype="float32"), 8, 64, 3,
                        600, dict(dp=2, fsdp=1, tp=2, pp=2,
                                  microbatches=4, overlap=True)),
    "tpu": (True, 0, dict(vocab_size=32768, hidden_size=1024,
                          num_layers=24, num_heads=16, max_seq_len=1024,
                          remat=True, remat_policy="dots",
                          dtype="bfloat16"), 8, 1024, 10, 2100, None),
    "tpu_overlap": (True, 0, dict(vocab_size=32768, hidden_size=1024,
                                  num_layers=24, num_heads=16,
                                  max_seq_len=1024, remat=True,
                                  remat_policy="dots",
                                  dtype="bfloat16"), 8, 1024, 10, 2100,
                    dict(overlap=True)),
}


def run_leg(name: str) -> None:
    """One leg, in-process: measure and print the inner JSON line."""
    want_tpu, n_dev, kw, batch, seq, iters, _t, degrees = LEGS[name]
    if not want_tpu:
        # pinned UNCONDITIONALLY (the env's TPU plugin overrides
        # JAX_PLATFORMS; a flapping tunnel would otherwise hang init)
        from paddle_tpu.device import pin_cpu
        if not pin_cpu(n_dev):
            log("could not pin the virtual CPU platform")
            sys.exit(17)
    else:
        from bench import apply_perf_env_defaults
        apply_perf_env_defaults()

    import jax
    import jax.numpy as jnp
    import numpy as np
    devs = jax.devices()
    platform = devs[0].platform
    if want_tpu and platform not in ("tpu", "axon"):
        log(f"wanted TPU, got {platform}; abandoning leg")
        sys.exit(17)
    n = n_dev or len(devs)
    from paddle_tpu.utils.compile_cache import sync_compile_cache_for
    sync_compile_cache_for(platform)

    from paddle_tpu.models.facade import make_train_step
    from paddle_tpu.models.gpt import (GPTConfig, init_gpt_params,
                                       init_opt_state, train_step)
    from paddle_tpu.parallel.planner import plan_train
    kw = dict(kw)
    kw["dtype"] = jnp.bfloat16 if kw["dtype"] == "bfloat16" else jnp.float32
    cfg = GPTConfig(sequence_parallel=False, **kw)
    plan = plan_train(cfg, n, batch, **(degrees or {}))
    log(f"leg={name} n={n} plan={plan.name} "
        f"({cfg.num_layers}L x {cfg.hidden_size}d, B={batch}, S={seq})")
    mesh = plan.build_mesh()
    params = init_gpt_params(cfg, jax.random.PRNGKey(0))
    opt = init_opt_state(params)
    toks = np.random.RandomState(1).randint(
        0, cfg.vocab_size, (batch, seq + 1)).astype(np.int32)
    step = make_train_step(train_step, cfg=cfg, lr=1e-4, mesh=mesh,
                           plan=plan)
    t0 = time.perf_counter()
    loss, params, opt = step(params, opt, toks)
    loss_v = float(loss)     # forces; block_until_ready unreliable (CLAUDE.md)
    log(f"  compile+first {time.perf_counter() - t0:.1f}s "
        f"(loss={loss_v:.4f})")
    t0 = time.perf_counter()
    for _ in range(iters):
        loss, params, opt = step(params, opt, toks)
    float(loss)              # forces the chained sequence
    dt = (time.perf_counter() - t0) / iters
    n_params = sum(int(v.size) for v in params.values())
    tps = batch * seq / dt
    from bench import _peak_for, train_flops_per_token
    flops_per_token = train_flops_per_token(
        n_params, cfg.num_layers, cfg.hidden_size, seq)
    # MFU against the WHOLE mesh's peak (n chips) — the multi-chip MFU
    # claim the ROADMAP's >=45% target is stated in
    mfu = flops_per_token * tps / (_peak_for(devs[0].device_kind,
                                             platform) * n)
    rec = {
        "metric": ("gpt_train_plan4d" if plan.pp > 1
                   else "gpt_train_plan3d"),
        "n_devices": n,
        "plan": plan.name,
        "backend": platform,
        "ms_per_step": round(dt * 1e3, 2),
        "tokens_per_sec": round(tps, 1),
        "mfu": round(mfu, 4),
        "traces_after_warmup": step.trace_count,
        "batch": batch, "seq": seq,
        "overlap": bool(getattr(plan, "overlap", False)),
    }
    if plan.pp > 1:
        rec["microbatches"] = plan.microbatches
        rec["bubble_fraction"] = round(
            float(getattr(step, "bubble_fraction", 0.0) or 0.0), 4)
    print(json.dumps(rec), flush=True)


def orchestrate(want_tpu: bool, want_pp: bool = False,
                want_overlap: bool = False) -> int:
    """Run the legs in subprocesses; print ONE MULTICHIP-format JSON
    line per leg ({"n_devices", "rc", "ok", "skipped", "tail"} + the
    measured record when the leg produced one)."""
    legs = ["cpu8"]
    if want_overlap:
        legs.append("cpu8_overlap")
    if want_pp:
        legs.append("cpu8_pp")
        if want_overlap:
            legs.append("cpu8_pp_overlap")
    if want_tpu:
        legs.append("tpu")
        if want_overlap:
            legs.append("tpu_overlap")
    worst = 0
    for name in legs:
        _wt, n_dev, _kw, _b, _s, _i, timeout_s, _deg = LEGS[name]
        if name.startswith("tpu"):
            from bench import _probe_tpu
            if not _probe_tpu(HERE):
                log("tunnel dead; TPU leg skipped")
                print(json.dumps({"n_devices": n_dev or 1, "rc": 0,
                                  "ok": False, "skipped": True,
                                  "tail": "tpu leg skipped: tunnel dead "
                                          "or probe disabled"}),
                      flush=True)
                continue
        try:
            res = subprocess.run(
                [sys.executable, os.path.abspath(__file__), "--run", name],
                cwd=HERE, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                timeout=timeout_s)
            rc, out, err = res.returncode, res.stdout, res.stderr
        except subprocess.TimeoutExpired as te:
            rc = -9
            out = te.stdout or b""
            err = (te.stderr or b"") + f"\n[timeout {timeout_s}s]".encode()
        tail = err.decode(errors="replace")[-2000:]
        line = next((ln for ln in reversed(
            out.decode(errors="replace").splitlines())
            if ln.startswith("{")), None)
        rec = {"n_devices": n_dev or 1, "rc": rc, "ok": False,
               "skipped": False, "tail": tail}
        if line:
            try:
                inner = json.loads(line)
                rec.update(inner)
                rec["ok"] = rc == 0
            except json.JSONDecodeError:
                pass
        print(json.dumps(rec), flush=True)
        if not rec["ok"] and not rec["skipped"]:
            worst = 1
    return worst


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--tpu", action="store_true",
                    help="also attempt the TPU leg (tunnel-gated)")
    ap.add_argument("--pp", action="store_true",
                    help="also run the cpu8_pp 4D (dp2×tp2×pp2) leg "
                         "(tpu_campaign --plan4d)")
    ap.add_argument("--overlap", action="store_true",
                    help="also run the overlap A/B legs (same grids, "
                         "latency-hiding collective schedule on)")
    ap.add_argument("--run", default=None, choices=sorted(LEGS),
                    help="run ONE leg in-process (orchestrator internal)")
    args = ap.parse_args()
    if args.run:
        run_leg(args.run)
        return 0
    return orchestrate(args.tpu, args.pp, args.overlap)


if __name__ == "__main__":
    sys.exit(main())
