#!/usr/bin/env python
"""HLO-audit regression gate: NO NEW RESHARDING, EVER.

PR 16 drove the canonical training plans to ZERO involuntary-resharding
findings (profiler/hlo_audit.py); this gate keeps them there. It
re-audits the canonical plans on the 8-virtual-device CPU mesh and
diffs the per-plan finding-kind counts against the stored baseline
(perf/audit_baseline.json):

- a finding KIND the baseline does not list for that plan  -> FAIL
- a listed kind whose count GREW                           -> FAIL
- fewer findings than baseline                             -> pass
  (with a note to --write-baseline and bank the win)

ONE exit code. Wired into `tools/chaos_drill.py --gate` (the pre-commit
robustness gate), so a refactor that re-introduces a GSPMD layout move
is caught before it lands, the same way diff_failures.py pins the
tier-1 failure set.

Usage:
  python tools/audit_gate.py                   # gate vs stored baseline
  python tools/audit_gate.py --write-baseline  # re-pin after a win
  python tools/audit_gate.py --plans fsdp8 --json
"""
import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)
TOOLS = os.path.dirname(os.path.abspath(__file__))
if TOOLS not in sys.path:
    sys.path.insert(0, TOOLS)

BASELINE_PATH = os.path.join(REPO, "perf", "audit_baseline.json")
# the canonical plan set: the two 3D acceptance plans of the MFU
# campaign plus the pipelined hybrid (BASELINE.md §MFU campaign)
CANONICAL_PLANS = ("dp2_fsdp2_tp2", "fsdp8", "dp2_tp2_pp2_mb4")


def finding_counts(audit: dict) -> dict:
    """{kind: count} over an audit_train_step result (or any dict
    carrying a findings list)."""
    counts = {}
    for f in audit.get("findings", []):
        k = f.get("kind", "unknown")
        counts[k] = counts.get(k, 0) + int(f.get("count", 1))
    return counts


def diff_counts(baseline: dict, observed: dict) -> list:
    """Regressions of one plan's observed {kind: count} vs its baseline
    {kind: count}: [(kind, base_count, seen_count), ...]. New kinds and
    grown counts regress; shrunk counts do not."""
    out = []
    for kind, seen in sorted(observed.items()):
        base = int(baseline.get(kind, 0))
        if seen > base:
            out.append((kind, base, seen))
    return out


def audit_plan(name: str):
    """Audit ONE canonical plan on the small observability config —
    the same cfg/batch/seq train_attrib measures, so the baseline and
    the attrib evidence describe the same lowering."""
    import train_attrib

    from paddle_tpu.models.gpt import PARAM_SPECS
    from paddle_tpu.parallel.planner import plan_train
    from paddle_tpu.profiler import hlo_audit

    class _Args:
        vocab, hidden, layers, seq = 512, 128, 2, 32

    cfg = train_attrib.build_cfg(_Args)
    deg = train_attrib.parse_plan_name(name)
    n_devices = deg["dp"] * deg["fsdp"] * deg["tp"] * deg.get("pp", 1)
    plan = plan_train(cfg, n_devices, 8, param_specs=PARAM_SPECS, **deg)
    return hlo_audit.audit_train_step(cfg, plan, 8, seq=_Args.seq)


def gate(plans, baseline_path: str, write: bool = False,
         as_json: bool = False) -> int:
    stored = {}
    if os.path.exists(baseline_path):
        with open(baseline_path) as f:
            stored = json.load(f)
    base_plans = stored.get("plans", {})
    observed, regressions, shrunk = {}, [], []
    for name in plans:
        counts = finding_counts(audit_plan(name))
        observed[name] = counts
        base = base_plans.get(name, {}).get("kinds", {})
        for kind, b, s in diff_counts(base, counts):
            regressions.append((name, kind, b, s))
        if sum(counts.values()) < sum(int(v) for v in base.values()):
            shrunk.append(name)
    if write:
        doc = {
            "comment": "HLO-audit finding baseline per canonical plan "
                       "(tools/audit_gate.py --write-baseline). The "
                       "gate fails on any NEW kind or grown count.",
            "plans": {n: {"findings": sum(c.values()), "kinds": c}
                      for n, c in observed.items()},
        }
        with open(baseline_path, "w") as f:
            json.dump(doc, f, indent=2, sort_keys=True)
            f.write("\n")
        print(f"[audit-gate] baseline written: {baseline_path}",
              flush=True)
        return 0
    if as_json:
        print(json.dumps({"metric": "hlo_audit_gate",
                          "observed": observed,
                          "regressions": [
                              {"plan": p, "kind": k, "baseline": b,
                               "seen": s}
                              for p, k, b, s in regressions]}),
              flush=True)
    for p, k, b, s in regressions:
        print(f"[audit-gate] REGRESSION {p}: {k} {b} -> {s}",
              flush=True)
    if regressions:
        print("[audit-gate] HLO AUDIT GATE RED "
              f"({len(regressions)} regressed kind(s))", flush=True)
        return 1
    for p in shrunk:
        print(f"[audit-gate] {p}: fewer findings than baseline — "
              "bank it with --write-baseline", flush=True)
    total = sum(sum(c.values()) for c in observed.values())
    print(f"[audit-gate] GREEN: {len(observed)} plan(s), "
          f"{total} finding(s), no new kinds", flush=True)
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description=__doc__.splitlines()[0])
    ap.add_argument("--plans", default=",".join(CANONICAL_PLANS),
                    help="comma-separated plan names to audit")
    ap.add_argument("--baseline", default=BASELINE_PATH)
    ap.add_argument("--write-baseline", action="store_true",
                    help="re-pin the stored baseline from this run")
    ap.add_argument("--json", action="store_true")
    args = ap.parse_args(argv)

    from paddle_tpu.device import pin_cpu
    if not pin_cpu(8):
        print("[audit-gate] could not pin the 8-device CPU platform",
              flush=True)
        return 2
    plans = [p for p in args.plans.split(",") if p]
    return gate(plans, args.baseline, write=args.write_baseline,
                as_json=args.json)


if __name__ == "__main__":
    sys.exit(main())
