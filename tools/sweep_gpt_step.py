"""Offline TPU sweep for the bench train step: remat policy x flash blocks.

Each variant runs in a fresh subprocess under a timeout (the tunnel can hang)
and prints one JSON line; the parent prints a ranked summary at the end.
Results feed the shipped defaults (GPTConfig.remat/remat_policy, the bench
ladder, and PADDLE_TPU_FLASH_BLOCK_* defaults) plus BASELINE.md.

Usage:  python tools/sweep_gpt_step.py            # orchestrate the sweep
        python tools/sweep_gpt_step.py --run '<json>'   # one variant (internal)
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
import time

VARIANTS = [
    # name, remat, policy, (bq, bk, bwd_q, bwd_k), extra env
    # round-3 kernels are bf16-operand MXU-native and the loss runs the
    # Pallas CE kernel by default: re-rank everything.
    ("dots-jaxbwd", True, "dots", (128, 128, 128, 128),
     {"PADDLE_TPU_DISABLE_PALLAS_BWD": "1"}),
    ("dots-pallasbwd", True, "dots", (128, 128, 128, 128), {}),
    ("full-jaxbwd", True, "full", (128, 128, 128, 128),
     {"PADDLE_TPU_DISABLE_PALLAS_BWD": "1"}),
    ("dots-jaxbwd-noCE", True, "dots", (128, 128, 128, 128),
     {"PADDLE_TPU_DISABLE_PALLAS_BWD": "1",
      "PADDLE_TPU_DISABLE_PALLAS_CE": "1"}),
    ("dots-nopallas", True, "dots", (128, 128, 128, 128),
     {"PADDLE_TPU_DISABLE_PALLAS": "1"}),
    ("dots-256", True, "dots", (256, 256, 256, 256), {}),
    ("dots-jaxbwd-q256k512", True, "dots", (256, 512, 128, 128),
     {"PADDLE_TPU_DISABLE_PALLAS_BWD": "1"}),
    ("dots-512", True, "dots", (512, 512, 512, 512), {}),
    # round-4 additions: scan unrolling (cross-block fusion), host-offloaded
    # dot saves (HBM headroom — the no-remat config OOMed at B=8), and the
    # unroll x jax-bwd combination
    ("dots-jaxbwd-unroll4", True, "dots", (128, 128, 128, 128),
     {"PADDLE_TPU_DISABLE_PALLAS_BWD": "1", "SWEEP_SCAN_UNROLL": "4"}),
    ("dots-jaxbwd-unroll2", True, "dots", (128, 128, 128, 128),
     {"PADDLE_TPU_DISABLE_PALLAS_BWD": "1", "SWEEP_SCAN_UNROLL": "2"}),
    ("offload-jaxbwd", True, "offload_dots", (128, 128, 128, 128),
     {"PADDLE_TPU_DISABLE_PALLAS_BWD": "1"}),
    # save the named flash outputs too: no attention fwd recompute in bwd
    ("dotsflash-jaxbwd", True, "dots_flash", (128, 128, 128, 128),
     {"PADDLE_TPU_DISABLE_PALLAS_BWD": "1"}),
    ("dotsflash-jaxbwd-unroll2", True, "dots_flash", (128, 128, 128, 128),
     {"PADDLE_TPU_DISABLE_PALLAS_BWD": "1", "SWEEP_SCAN_UNROLL": "2"}),
]

MODEL = dict(vocab_size=32768, hidden_size=1024, num_layers=24,
             num_heads=16, max_seq_len=1024)
BATCH, SEQ, ITERS = 8, 1024, 8


def run_one(spec: dict) -> None:
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    import jax
    import jax.numpy as jnp
    import functools
    from paddle_tpu.models.gpt import (GPTConfig, init_gpt_params,
                                       init_opt_state, train_step)
    devs = jax.devices()
    cfg = GPTConfig(sequence_parallel=False, remat=spec["remat"],
                    remat_policy=spec["policy"], dtype=jnp.bfloat16,
                    scan_unroll=int(os.environ.get("SWEEP_SCAN_UNROLL",
                                                   "1")),
                    **MODEL)
    params = init_gpt_params(cfg, jax.random.PRNGKey(0))
    opt_state = init_opt_state(params)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (BATCH, SEQ + 1), 0,
                                cfg.vocab_size)
    step = jax.jit(functools.partial(train_step, cfg=cfg, lr=1e-4),
                   donate_argnums=(0, 1))
    t0 = time.perf_counter()
    loss, params, opt_state = step(params, opt_state, tokens)
    float(loss)
    compile_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    for _ in range(ITERS):
        loss, params, opt_state = step(params, opt_state, tokens)
    float(loss)
    dt = (time.perf_counter() - t0) / ITERS
    print(json.dumps({"name": spec["name"], "ms_per_step": round(dt * 1e3, 2),
                      "tokens_per_sec": round(BATCH * SEQ / dt, 1),
                      "compile_s": round(compile_s, 1),
                      "platform": devs[0].platform}), flush=True)


def main() -> None:
    here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    results = []
    for name, remat, policy, (bq, bk, bwq, bwk), extra in VARIANTS:
        spec = {"name": name, "remat": remat, "policy": policy}
        env = dict(os.environ)
        env.update({
            "PADDLE_TPU_FLASH_BLOCK_Q": str(bq),
            "PADDLE_TPU_FLASH_BLOCK_K": str(bk),
            "PADDLE_TPU_FLASH_BLOCK_BWD_Q": str(bwq),
            "PADDLE_TPU_FLASH_BLOCK_BWD_K": str(bwk),
        })
        env.update(extra)
        print(f"[sweep] === {name} ===", file=sys.stderr, flush=True)
        try:
            res = subprocess.run(
                [sys.executable, os.path.abspath(__file__), "--run",
                 json.dumps(spec)],
                cwd=here, env=env, stdout=subprocess.PIPE, timeout=900)
        except subprocess.TimeoutExpired:
            print(f"[sweep] {name}: TIMEOUT", file=sys.stderr, flush=True)
            continue
        out = res.stdout.decode().strip().splitlines()
        line = next((ln for ln in reversed(out) if ln.startswith("{")), None)
        if res.returncode == 0 and line:
            rec = json.loads(line)
            results.append(rec)
            print(f"[sweep] {name}: {rec['ms_per_step']} ms/step",
                  file=sys.stderr, flush=True)
        else:
            print(f"[sweep] {name}: FAILED rc={res.returncode}",
                  file=sys.stderr, flush=True)
    results.sort(key=lambda r: r["ms_per_step"])
    print(json.dumps({"ranked": results}, indent=1), flush=True)


if __name__ == "__main__":
    if len(sys.argv) >= 3 and sys.argv[1] == "--run":
        run_one(json.loads(sys.argv[2]))
    else:
        main()
