"""Offline TPU sweep for the bench train step: remat policy x flash blocks.

Each variant runs in a fresh subprocess under a timeout (the tunnel can hang)
and prints one JSON line; the parent prints a ranked summary at the end.
Results feed the shipped defaults (GPTConfig.remat/remat_policy, the bench
ladder, and PADDLE_TPU_FLASH_BLOCK_* defaults) plus BASELINE.md.

Usage:  python tools/sweep_gpt_step.py            # orchestrate the sweep
        python tools/sweep_gpt_step.py --run '<json>'   # one variant (internal)
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
import time

JAXBWD = {"PADDLE_TPU_DISABLE_PALLAS_BWD": "1"}
XLA_ATTN = {"PADDLE_TPU_DISABLE_PALLAS_ATTN": "1", **JAXBWD}

VARIANTS = [
    # name, remat, policy, (bq, bk, bwd_q, bwd_k), extra env[, batch]
    # Ordered by the round-4 ablation matrix (perf/window_*/ablate.out):
    # no-remat at reduced batch beat every remat variant per-token
    # (42.5 ms/sample at B=4 vs 53.4 best remat at B=8), and the XLA
    # attention path beat the Pallas flash fwd in the full step (399.7 vs
    # 435.5 ms). Race the combos; tokens_per_sec is the cross-batch metric.
    # Default blocks are the round-4 autotune winners (perf/autotune.json:
    # fwd 512/256 measured 3.4x faster than the old 128/128; bwd 128/128).
    # Explicit FLASH_BLOCK env settings outrank the autotune cache, so
    # these tuples really do control every variant.
    # HIGHEST-VALUE HYPOTHESES FIRST: a congested window may only get
    # through a handful of variants before the tunnel drops.
    # all_but_mlp: nested checkpoint around just the dense FFN (block
    # otherwise unremat'd) — near-no-remat memory at full batch (true
    # no-remat OOMs at B=8); splash = upstream block-sparse kernel (the
    # homegrown kernel measured ~6 TF/s effective in the ablation)
    ("allbutmlp-splash-b8", True, "all_but_mlp", (512, 256, 128, 128),
     {"PADDLE_TPU_ATTN_IMPL": "splash"}),
    ("allbutmlp-b8", True, "all_but_mlp", (512, 256, 128, 128), JAXBWD),
    ("splash-dotsflash-b8", True, "dots_flash", (512, 256, 128, 128),
     {"PADDLE_TPU_ATTN_IMPL": "splash"}),
    ("noremat-b4", False, "dots", (512, 256, 128, 128), JAXBWD, 4),
    ("splash-noremat-b4", False, "dots", (512, 256, 128, 128),
     {"PADDLE_TPU_ATTN_IMPL": "splash"}, 4),
    # same-window baseline for honest deltas vs r02/r03 numbers
    ("dots-jaxbwd", True, "dots", (512, 256, 128, 128), JAXBWD),
    ("jaxflash-dotsflash-b8", True, "dots_flash", (512, 256, 128, 128),
     {"PADDLE_TPU_ATTN_IMPL": "jax_flash"}),
    # opportunistic: larger batch if the memory shape allows (OOM is
    # caught and the variant skipped)
    ("allbutmlp-splash-b12", True, "all_but_mlp", (512, 256, 128, 128),
     {"PADDLE_TPU_ATTN_IMPL": "splash"}, 12),
    ("jaxflash-noremat-b4", False, "dots", (512, 256, 128, 128),
     {"PADDLE_TPU_ATTN_IMPL": "jax_flash"}, 4),
    ("noremat-xlaattn-b4", False, "dots", (512, 256, 128, 128),
     XLA_ATTN, 4),
    ("noremat-b6", False, "dots", (512, 256, 128, 128), JAXBWD, 6),
    ("noremat-pallasbwd-b4", False, "dots", (512, 256, 128, 128), {}, 4),
    # autotune's bwd microbench flipped the round-3 verdict (Pallas bwd
    # 116 ms vs jax-level 170.6): re-litigate at step level, tuned blocks
    ("dots-pallasbwd-tuned", True, "dots", (512, 256, 128, 128), {}),
    ("dotsflash-jaxbwd", True, "dots_flash", (512, 256, 128, 128), JAXBWD),
    ("xlaattn-dots-b8", True, "dots", (512, 256, 128, 128), XLA_ATTN, 8),
    ("noremat-b5", False, "dots", (512, 256, 128, 128), JAXBWD, 5),
    # host-offloaded dot saves: HBM headroom without recompute
    ("offload-jaxbwd", True, "offload_dots", (512, 256, 128, 128), JAXBWD),
    ("dotsflash-jaxbwd-unroll2", True, "dots_flash", (512, 256, 128, 128),
     {**JAXBWD, "SWEEP_SCAN_UNROLL": "2"}),
    ("noremat-xlaattn-b6", False, "dots", (512, 256, 128, 128),
     XLA_ATTN, 6),
    ("dots-jaxbwd-noCE", True, "dots", (512, 256, 128, 128),
     {**JAXBWD, "PADDLE_TPU_DISABLE_PALLAS_CE": "1"}),
]

MODEL = dict(vocab_size=32768, hidden_size=1024, num_layers=24,
             num_heads=16, max_seq_len=1024)
BATCH, SEQ, ITERS = 8, 1024, 8


def run_one(spec: dict) -> None:
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    import jax
    import jax.numpy as jnp
    import functools
    from paddle_tpu.models.gpt import (GPTConfig, init_gpt_params,
                                       init_opt_state, train_step)
    devs = jax.devices()
    cfg = GPTConfig(sequence_parallel=False, remat=spec["remat"],
                    remat_policy=spec["policy"], dtype=jnp.bfloat16,
                    scan_unroll=int(os.environ.get("SWEEP_SCAN_UNROLL",
                                                   "1")),
                    **MODEL)
    batch = int(spec.get("batch", BATCH))
    params = init_gpt_params(cfg, jax.random.PRNGKey(0))
    opt_state = init_opt_state(params)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (batch, SEQ + 1), 0,
                                cfg.vocab_size)
    step = jax.jit(functools.partial(train_step, cfg=cfg, lr=1e-4),
                   donate_argnums=(0, 1))
    t0 = time.perf_counter()
    loss, params, opt_state = step(params, opt_state, tokens)
    float(loss)
    compile_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    for _ in range(ITERS):
        loss, params, opt_state = step(params, opt_state, tokens)
    float(loss)
    dt = (time.perf_counter() - t0) / ITERS
    print(json.dumps({"name": spec["name"], "ms_per_step": round(dt * 1e3, 2),
                      "tokens_per_sec": round(batch * SEQ / dt, 1),
                      "batch": batch, "compile_s": round(compile_s, 1),
                      "platform": devs[0].platform}), flush=True)


def main() -> None:
    here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    # feeds only the CE kernel's block lookup — every variant pins the
    # four FLASH_BLOCK vars, which outrank the cache
    cache = os.path.join(here, "perf", "autotune.json")
    results = []
    for name, remat, policy, (bq, bk, bwq, bwk), extra, *rest in VARIANTS:
        spec = {"name": name, "remat": remat, "policy": policy}
        if rest:
            spec["batch"] = rest[0]
        env = dict(os.environ)
        if os.path.exists(cache):
            env.setdefault("PADDLE_TPU_AUTOTUNE_CACHE", cache)
        env.update({
            "PADDLE_TPU_FLASH_BLOCK_Q": str(bq),
            "PADDLE_TPU_FLASH_BLOCK_K": str(bk),
            "PADDLE_TPU_FLASH_BLOCK_BWD_Q": str(bwq),
            "PADDLE_TPU_FLASH_BLOCK_BWD_K": str(bwk),
        })
        env.update(extra)
        print(f"[sweep] === {name} ===", file=sys.stderr, flush=True)
        try:
            res = subprocess.run(
                [sys.executable, os.path.abspath(__file__), "--run",
                 json.dumps(spec)],
                cwd=here, env=env, stdout=subprocess.PIPE, timeout=900)
        except subprocess.TimeoutExpired:
            print(f"[sweep] {name}: TIMEOUT", file=sys.stderr, flush=True)
            continue
        out = res.stdout.decode().strip().splitlines()
        line = next((ln for ln in reversed(out) if ln.startswith("{")), None)
        if res.returncode == 0 and line:
            rec = json.loads(line)
            results.append(rec)
            print(f"[sweep] {name}: {rec['ms_per_step']} ms/step",
                  file=sys.stderr, flush=True)
        else:
            print(f"[sweep] {name}: FAILED rc={res.returncode}",
                  file=sys.stderr, flush=True)
    # batches differ across variants: rank by throughput, not step time
    results.sort(key=lambda r: -r["tokens_per_sec"])
    print(json.dumps({"ranked": results}, indent=1), flush=True)


if __name__ == "__main__":
    if len(sys.argv) >= 3 and sys.argv[1] == "--run":
        run_one(json.loads(sys.argv[2]))
    else:
        main()
