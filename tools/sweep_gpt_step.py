"""Offline TPU sweep for the bench train step: attention impl x remat
policy x batch (x flash blocks via env).

Variants run IN-PROCESS inside one child (one interpreter + jax import +
backend init for the whole list — per-variant subprocesses burned
~25-40 s of scarce tunnel-window time each). The orchestrator watches
the child's stdout and respawns it with the remaining variants if it
crashes (e.g. a Mosaic abort) or stalls past the per-variant budget
(tunnel hang), dropping only the variant that was in flight. Every
variant's env (kill switches, impl selector, flash blocks) is applied
around its own run from a whole-env snapshot, and every gate re-reads
env per trace, so in-process racing is sound — bench.py races its
variants the same way.

Each variant prints one JSON line; the parent prints a ranked summary
(by tokens/sec — batches differ) at the end. Results feed the shipped
defaults (GPTConfig.remat/remat_policy, PADDLE_TPU_ATTN_IMPL, the bench
ladder, PADDLE_TPU_FLASH_BLOCK_* defaults) plus BASELINE.md.

Usage:  python tools/sweep_gpt_step.py                 # orchestrate
        python tools/sweep_gpt_step.py --run-list '<json>'   # internal
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
import time

JAXBWD = {"PADDLE_TPU_DISABLE_PALLAS_BWD": "1"}
XLA_ATTN = {"PADDLE_TPU_DISABLE_PALLAS_ATTN": "1", **JAXBWD}

VARIANTS = [
    # name, remat, policy, (bq, bk, bwd_q, bwd_k), extra env[, batch]
    # Ordered by the round-4 ablation matrix (perf/window_*/ablate.out):
    # no-remat at reduced batch beat every remat variant per-token
    # (42.5 ms/sample at B=4 vs 53.4 best remat at B=8), and attention
    # is ~66% of the step. Default blocks are the round-4 autotune
    # winners (perf/autotune.json: fwd 512/256; bwd 128/128). Explicit
    # FLASH_BLOCK env settings outrank the autotune cache, so these
    # tuples really do control every variant.
    # HIGHEST-VALUE HYPOTHESES FIRST: a congested window may only get
    # through a handful of variants before the tunnel drops.
    # all_but_mlp: nested checkpoint around just the dense FFN (block
    # otherwise unremat'd) — near-no-remat memory at full batch (true
    # no-remat OOMs at B=8); splash = upstream block-sparse kernel (the
    # homegrown kernel measured ~6 TF/s effective in the ablation)
    ("allbutmlp-splash-b8", True, "all_but_mlp", (512, 256, 128, 128),
     {"PADDLE_TPU_ATTN_IMPL": "splash"}),
    # cheapest remat x the attention impl the window-1 ablation crowned
    # (xla 399.7 ms vs 427+ for every pallas fwd) — the most likely
    # winner cross, so it races near the front
    ("allbutmlp-xlaattn-b8", True, "all_but_mlp", (512, 256, 128, 128),
     XLA_ATTN),
    ("allbutmlp-b8", True, "all_but_mlp", (512, 256, 128, 128), JAXBWD),
    ("splash-dotsflash-b8", True, "dots_flash", (512, 256, 128, 128),
     {"PADDLE_TPU_ATTN_IMPL": "splash"}),
    ("noremat-b4", False, "dots", (512, 256, 128, 128), JAXBWD, 4),
    ("splash-noremat-b4", False, "dots", (512, 256, 128, 128),
     {"PADDLE_TPU_ATTN_IMPL": "splash"}, 4),
    # same-window baseline for honest deltas vs r02/r03 numbers
    ("dots-jaxbwd", True, "dots", (512, 256, 128, 128), JAXBWD),
    ("jaxflash-dotsflash-b8", True, "dots_flash", (512, 256, 128, 128),
     {"PADDLE_TPU_ATTN_IMPL": "jax_flash"}),
    # opportunistic: larger batch if the memory shape allows (OOM is
    # caught and the variant skipped)
    ("allbutmlp-splash-b12", True, "all_but_mlp", (512, 256, 128, 128),
     {"PADDLE_TPU_ATTN_IMPL": "splash"}, 12),
    ("jaxflash-noremat-b4", False, "dots", (512, 256, 128, 128),
     {"PADDLE_TPU_ATTN_IMPL": "jax_flash"}, 4),
    ("noremat-xlaattn-b4", False, "dots", (512, 256, 128, 128),
     XLA_ATTN, 4),
    ("noremat-b6", False, "dots", (512, 256, 128, 128), JAXBWD, 6),
    ("noremat-pallasbwd-b4", False, "dots", (512, 256, 128, 128), {}, 4),
    # autotune's bwd microbench flipped the round-3 verdict (Pallas bwd
    # 116 ms vs jax-level 170.6): re-litigate at step level, tuned blocks
    ("dots-pallasbwd-tuned", True, "dots", (512, 256, 128, 128), {}),
    ("dotsflash-jaxbwd", True, "dots_flash", (512, 256, 128, 128), JAXBWD),
    ("xlaattn-dots-b8", True, "dots", (512, 256, 128, 128), XLA_ATTN, 8),
    ("noremat-b5", False, "dots", (512, 256, 128, 128), JAXBWD, 5),
    # host-offloaded dot saves: HBM headroom without recompute
    ("offload-jaxbwd", True, "offload_dots", (512, 256, 128, 128), JAXBWD),
    ("dotsflash-jaxbwd-unroll2", True, "dots_flash", (512, 256, 128, 128),
     {**JAXBWD, "SWEEP_SCAN_UNROLL": "2"}),
    ("noremat-xlaattn-b6", False, "dots", (512, 256, 128, 128),
     XLA_ATTN, 6),
    ("dots-jaxbwd-noCE", True, "dots", (512, 256, 128, 128),
     {**JAXBWD, "PADDLE_TPU_DISABLE_PALLAS_CE": "1"}),
]

MODEL = dict(vocab_size=32768, hidden_size=1024, num_layers=24,
             num_heads=16, max_seq_len=1024)
BATCH, SEQ, ITERS = 8, 1024, 8
VARIANT_BUDGET_S = 900      # stall bound: no output for this long → kill


def _specs() -> list:
    """VARIANTS table → self-contained spec dicts (env folded in)."""
    specs = []
    for name, remat, policy, (bq, bk, bwq, bwk), extra, *rest in VARIANTS:
        env = {
            "PADDLE_TPU_FLASH_BLOCK_Q": str(bq),
            "PADDLE_TPU_FLASH_BLOCK_K": str(bk),
            "PADDLE_TPU_FLASH_BLOCK_BWD_Q": str(bwq),
            "PADDLE_TPU_FLASH_BLOCK_BWD_K": str(bwk),
            **extra,
        }
        specs.append({"name": name, "remat": remat, "policy": policy,
                      "env": env, "batch": rest[0] if rest else BATCH})
    return specs


def _child_env() -> dict:
    """Env for the child INTERPRETER (not per-variant): the autotune
    cache path is read by kernels/autotune.py at module import time, so
    per-variant application would be a silent no-op — it is uniform
    across variants anyway (feeds only the CE kernel's block lookup;
    every variant pins the FLASH_BLOCK vars, which outrank the cache)."""
    here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    cache = os.path.join(here, "perf", "autotune.json")
    if os.path.exists(cache):
        env.setdefault("PADDLE_TPU_AUTOTUNE_CACHE", cache)
    return env


def run_one(spec: dict) -> None:
    """One variant, in the current process; env applied from a snapshot
    (all kernel gates re-read env per trace)."""
    import jax
    import jax.numpy as jnp

    from paddle_tpu.models.gpt import (GPTConfig, init_gpt_params,
                                       init_opt_state, train_step)
    snapshot = dict(os.environ)
    try:
        os.environ.update(spec.get("env", {}))
        devs = jax.devices()
        cfg = GPTConfig(sequence_parallel=False, remat=spec["remat"],
                        remat_policy=spec["policy"], dtype=jnp.bfloat16,
                        scan_unroll=int(os.environ.get(
                            "SWEEP_SCAN_UNROLL", "1")),
                        **spec.get("model", MODEL))
        batch = int(spec.get("batch", BATCH))
        seq = int(spec.get("seq", SEQ))
        params = init_gpt_params(cfg, jax.random.PRNGKey(0))
        opt_state = init_opt_state(params)
        tokens = jax.random.randint(jax.random.PRNGKey(1),
                                    (batch, seq + 1), 0, cfg.vocab_size)
        from paddle_tpu.models.facade import make_train_step
        step = make_train_step(train_step, cfg=cfg, lr=1e-4)
        t0 = time.perf_counter()
        loss, params, opt_state = step(params, opt_state, tokens)
        float(loss)
        compile_s = time.perf_counter() - t0
        t0 = time.perf_counter()
        for _ in range(ITERS):
            loss, params, opt_state = step(params, opt_state, tokens)
        float(loss)
        dt = (time.perf_counter() - t0) / ITERS
        print(json.dumps({"name": spec["name"],
                          "ms_per_step": round(dt * 1e3, 2),
                          "tokens_per_sec": round(batch * seq / dt, 1),
                          "batch": batch, "compile_s": round(compile_s, 1),
                          "platform": devs[0].platform}), flush=True)
    finally:
        os.environ.clear()
        os.environ.update(snapshot)


def run_list(specs: list) -> None:
    """Child entry: race every spec in this one process. A failed
    variant (OOM, Mosaic error) is reported and skipped; a hard crash
    ends the process and the orchestrator respawns with the rest."""
    if os.environ.get("SWEEP_PIN_CPU") == "1":
        # dev/smoke hook: the axon plugin hijacks backend init even
        # under JAX_PLATFORMS=cpu (CLAUDE.md trap) — only pin_cpu works
        from paddle_tpu.device import pin_cpu
        pin_cpu(1)
    for spec in specs:
        print(f"[sweep-child] === {spec['name']} ===", file=sys.stderr,
              flush=True)
        if spec.get("_crash"):      # orchestrator-respawn test hook
            os._exit(9)
        try:
            run_one(spec)
        except Exception as e:
            print(json.dumps({"name": spec["name"],
                              "error": repr(e)[:200]}), flush=True)


def main() -> None:
    here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    pending = _specs()
    results, failed = [], []

    while pending:
        proc = subprocess.Popen(
            [sys.executable, os.path.abspath(__file__), "--run-list",
             json.dumps(pending)],
            cwd=here, env=_child_env(), stdout=subprocess.PIPE)
        done_this_child = 0
        import select
        fd = proc.stdout.fileno()
        buf = b""
        # the stall deadline is measured from the last ACCEPTED record —
        # stray stdout noise (jax/libtpu retry chatter) must not keep a
        # hung variant alive, and raw os.read avoids the buffered-
        # readline-vs-select trap where a completed record sits unread
        last_rec = time.time()

        def handle(raw: bytes) -> None:
            nonlocal done_this_child, last_rec
            # a record is only the next pending variant's line — noise
            # must neither crash the sweep nor desync the pending slice
            try:
                rec = json.loads(raw.decode(errors="replace").strip())
            except ValueError:
                return
            if (done_this_child >= len(pending)
                    or not isinstance(rec, dict)
                    or rec.get("name") !=
                    pending[done_this_child]["name"]):
                return
            done_this_child += 1
            last_rec = time.time()
            if "error" in rec:
                failed.append(rec)
                print(f"[sweep] {rec['name']}: FAILED "
                      f"{rec['error'][:80]}", file=sys.stderr, flush=True)
            else:
                results.append(rec)
                print(f"[sweep] {rec['name']}: {rec['ms_per_step']} "
                      f"ms/step ({rec['tokens_per_sec']} tok/s)",
                      file=sys.stderr, flush=True)

        while True:
            r, _, _ = select.select([fd], [], [], 10.0)
            if r:
                chunk = os.read(fd, 65536)
                if not chunk:
                    if buf:
                        handle(buf)            # unterminated final line
                    break                      # EOF: child exited
                buf += chunk
                while b"\n" in buf:
                    line, buf = buf.split(b"\n", 1)
                    handle(line)
            elif proc.poll() is not None:
                if buf:
                    handle(buf)
                break
            # checked EVERY iteration — stdout noise must not postpone
            # the deadline (only accepted records reset last_rec)
            if time.time() - last_rec > VARIANT_BUDGET_S:
                # in-flight variant hung (tunnel): kill, drop it, respawn
                proc.kill()
                proc.wait()
                break
        if proc.poll() is None:
            proc.wait()
        survived = pending[done_this_child:]
        if proc.returncode == 0 and done_this_child >= len(pending):
            pending = []
        elif survived:
            dropped = survived[0]
            print(f"[sweep] child died/stalled on {dropped['name']}; "
                  f"dropping it, {len(survived) - 1} remain",
                  file=sys.stderr, flush=True)
            failed.append({"name": dropped["name"],
                           "error": "child crashed or stalled"})
            pending = survived[1:]
        else:
            pending = []

    # batches differ across variants: rank by throughput, not step time
    results.sort(key=lambda r: -r["tokens_per_sec"])
    print(json.dumps({"ranked": results, "failed": failed}, indent=1),
          flush=True)


if __name__ == "__main__":
    if len(sys.argv) >= 3 and sys.argv[1] == "--run-list":
        sys.path.insert(0, os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        run_list(json.loads(sys.argv[2]))
    else:
        main()
