"""Roofline attribution for the serving tick: measured ms vs the
cost-model ledger, per phase, per layout.

Joins the two halves this PR's observability layer provides:
- measured per-tick milliseconds + per-tick workload (active slots,
  attended cache tokens) from the in-tick telemetry stream
  (profiler/serving_telemetry — the fields ride the tick's one host
  pull, so the measurement perturbs nothing);
- the analytical per-phase FLOPs/bytes price of that workload
  (paddle_tpu.cost_model.serving_tick_ledger: attention math vs KV
  gather vs matmuls vs dequant epilogue vs LM head).

For each layout it reports the roofline lower bound per tick (each
phase at max(flops/peak, bytes/bw), the binding side named), the
measured p50 tick, the achieved-vs-roofline fraction, and the phase
attribution shares — the CPU-provable half of the ROADMAP MFU
campaign: the ledger and attribution math are platform-free, and on
the CPU rung the "achieved" column calibrates the harness (the
absolute fraction is only meaningful against the chip the roofline
describes; run with --tpu on a real window for the MFU number).

Usage:
  python tools/serving_attrib.py                  # dense-fp + paged-int8
  python tools/serving_attrib.py --pretty         # + human table
  python tools/serving_attrib.py --spec           # add a spec layout
  python tools/serving_attrib.py --peak-flops 2e14 --hbm-bw 8e11
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

# CPU unconditionally: the axon tunnel flaps and ANY backend init then
# hangs (CLAUDE.md trap) — pass --tpu to run on the default backend
from paddle_tpu.device import pin_cpu            # noqa: E402
if "--tpu" not in sys.argv:
    pin_cpu(1)

import numpy as np                               # noqa: E402
import jax                                       # noqa: E402
import jax.numpy as jnp                          # noqa: E402


def _log(msg):
    print(f"[serving_attrib] {msg}", flush=True)


def _pct(ordered, q):
    import math
    return ordered[max(0, math.ceil(q / 100.0 * len(ordered)) - 1)]


def build_model(hidden, layers, vocab, max_len):
    from paddle_tpu.models.gpt import GPTConfig, init_gpt_params
    cfg = GPTConfig(vocab_size=vocab, hidden_size=hidden,
                    num_layers=layers, num_heads=max(hidden // 32, 1),
                    ffn_hidden=4 * hidden, max_seq_len=2 * max_len,
                    sequence_parallel=False, remat=False,
                    dtype=jnp.float32)
    return init_gpt_params(cfg, jax.random.PRNGKey(0)), cfg


def measure_layout(name, params, cfg, prompts, gen, max_len,
                   engine_kw, peak_flops, hbm_bw):
    """One layout: warm, run measured, join tick telemetry with the
    ledger into the attribution row."""
    from paddle_tpu.inference.serving import ServingEngine
    from paddle_tpu.cost_model import (serving_tick_ledger,
                                       roofline_attribution)
    eng = ServingEngine(params, cfg, family="gpt", max_len=max_len,
                        telemetry="on", **engine_kw)
    eng.generate(prompts, gen)                 # warm (compiles)
    n0 = len(eng.tick_records())
    t0 = time.perf_counter()
    eng.generate(prompts, gen)
    wall_s = time.perf_counter() - t0
    recs = eng.tick_records()[n0:]
    ticks = [r for r in recs if r["kind"] == "serving_tick"]
    if not ticks:
        raise RuntimeError(f"{name}: no serving_tick records — "
                           "telemetry off?")
    dur = sorted(r["dur_ms"] for r in ticks)
    mean_active = float(np.mean([r["active"] for r in ticks]))
    mean_attended = float(np.mean([r["attended"] for r in ticks]))
    tokens = sum(r["tokens"] for r in ticks)

    ledger = serving_tick_ledger(
        cfg, family="gpt",
        layout="paged" if eng.paged else "dense",
        quant="int8" if eng.quant else "off",
        spec=bool(eng.spec),
        gamma=eng.spec_gamma if eng.spec else 0,
        draft_layers=eng.spec_draft_layers if eng.spec else 0,
        active=mean_active, attended=mean_attended,
        num_slots=eng.num_slots,     # the tick computes EVERY row
        max_len=eng.max_len, page_size=eng.page_size,
        max_pages=getattr(eng, "max_pages", 0))
    roof = roofline_attribution(ledger, peak_flops=peak_flops,
                                hbm_bw=hbm_bw)
    measured_ms = _pct(dur, 50)
    roof_ms = roof["roofline_s"] * 1e3
    row = {
        "layout": name,
        "ticks": len(ticks),
        "tokens": tokens,
        "tokens_per_s": round(tokens / wall_s, 1),
        "measured_ms_per_tick_p50": round(measured_ms, 3),
        "measured_ms_per_tick_p95": round(_pct(dur, 95), 3),
        "mean_active_slots": round(mean_active, 2),
        "mean_attended_tokens": round(mean_attended, 1),
        "tick_flops": round(ledger["total"]["flops"]),
        "tick_bytes": round(ledger["total"]["bytes"]),
        "roofline_ms_per_tick": round(roof_ms, 6),
        "achieved_vs_roofline": round(roof_ms / measured_ms, 6)
        if measured_ms else None,
        "phases": {
            p: {"share": v["share"], "bound": v["bound"],
                "flops": round(v["flops"]),
                "bytes": round(v["bytes"])}
            for p, v in roof["per_phase"].items()},
        "kv_masked_waste": round(
            1.0 - (ledger["phases"]["kv_gather"]["bytes_ideal"]
                   / ledger["phases"]["kv_gather"]["bytes"]), 4)
        if ledger["phases"]["kv_gather"]["bytes"] else 0.0,
        # dispatched vs useful attention flops: occupancy + mask waste
        "attn_useful_fraction": round(
            ledger["phases"]["attention"]["flops_useful"]
            / ledger["phases"]["attention"]["flops"], 4)
        if ledger["phases"]["attention"]["flops"] else 0.0,
    }
    return row


def render_table(rows) -> str:
    """The human-readable achieved-vs-roofline table."""
    lines = []
    hdr = (f"{'layout':<14} {'ms/tick':>9} {'roofline':>10} "
           f"{'achieved':>9}  phase shares (bound)")
    lines.append(hdr)
    lines.append("-" * len(hdr))
    for r in rows:
        shares = "  ".join(
            f"{p}={v['share']:.0%}({v['bound'][0]})"
            for p, v in sorted(r["phases"].items(),
                               key=lambda kv: -kv[1]["share"])
            if v["share"] > 0)
        lines.append(
            f"{r['layout']:<14} {r['measured_ms_per_tick_p50']:>9.3f} "
            f"{r['roofline_ms_per_tick']:>10.4f} "
            f"{r['achieved_vs_roofline']:>9.2%}  {shares}")
    return "\n".join(lines)


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--requests", type=int, default=8)
    ap.add_argument("--gen", type=int, default=16)
    ap.add_argument("--slots", type=int, default=4)
    ap.add_argument("--hidden", type=int, default=128)
    ap.add_argument("--layers", type=int, default=2)
    ap.add_argument("--vocab", type=int, default=512)
    ap.add_argument("--max-len", type=int, default=128)
    ap.add_argument("--page-size", type=int, default=16)
    ap.add_argument("--spec", action="store_true",
                    help="add a speculative layout (gamma=4)")
    ap.add_argument("--tpu", action="store_true",
                    help="run on the default (TPU) backend")
    ap.add_argument("--peak-flops", type=float, default=None,
                    help="roofline peak FLOP/s (default: "
                         "planner.ChipSpec)")
    ap.add_argument("--hbm-bw", type=float, default=None,
                    help="roofline bytes/s (default: planner.ChipSpec)")
    ap.add_argument("--pretty", action="store_true")
    args = ap.parse_args()

    params, cfg = build_model(args.hidden, args.layers, args.vocab,
                              args.max_len)
    rng = np.random.RandomState(0)
    prompts = [rng.randint(0, args.vocab,
                           rng.randint(8, 48)).astype(np.int32)
               for _ in range(args.requests)]
    layouts = [
        ("dense_fp", {"num_slots": args.slots, "kv_layout": "dense",
                      "quant": "off"}),
        ("paged_int8", {"num_slots": args.slots, "kv_layout": "paged",
                        "page_size": args.page_size, "quant": "int8"}),
    ]
    if args.spec:
        layouts.append(
            ("dense_fp_spec", {"num_slots": args.slots,
                               "kv_layout": "dense", "quant": "off",
                               "spec_decode": "spec", "gamma": 4}))
    rows = []
    for name, kw in layouts:
        _log(f"measuring {name} ...")
        rows.append(measure_layout(name, params, cfg, prompts,
                                   args.gen, args.max_len, kw,
                                   args.peak_flops, args.hbm_bw))
    doc = {"metric": "serving_roofline_attribution",
           "backend": jax.devices()[0].platform,
           "model": f"{args.layers}Lx{args.hidden}d",
           "requests": args.requests, "gen": args.gen,
           "layouts": rows}
    print(json.dumps(doc), flush=True)
    if args.pretty:
        print(render_table(rows), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
