"""Tunnel-burst measurement campaign (round-4 VERDICT item 1).

The axon TPU tunnel flaps in multi-hour windows; live minutes are scarce.
This orchestrator probes the tunnel cheaply on a loop and, the moment a
probe succeeds, drains a priority queue of measurement jobs — ablation
matrix, kernel autotune sweep, step-variant A/B, headline bench, ladder
rows — each in a subprocess with stdout/stderr captured to files so a
window that closes mid-job still yields every JSON line emitted before
the kill (VERDICT round-3 weak #4: hardware evidence must survive a dead
tunnel).

Artifacts:
  perf/window_<ts>/<job>.out|.err   raw per-job output (partial on kill)
  perf/campaign_state.json          job ledger (resume across restarts)
  BENCH_window_<ts>.json            repo-root aggregate: every JSON line
                                    measured in that window, timestamped
  perf/TPU_BUSY                     lockfile while a job is running, so
                                    local work can avoid contending with
                                    timing runs (the host has ONE core)

Usage:
  python tools/tpu_campaign.py                 # default phase-1 queue
  python tools/tpu_campaign.py --jobs bench,ladder_resnet50
  python tools/tpu_campaign.py --once          # one probe, no sleep loop
"""
from __future__ import annotations

import argparse
import datetime
import json
import os
import signal
import subprocess
import sys
import time

HERE = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PERF = os.path.join(HERE, "perf")
STATE_PATH = os.path.join(PERF, "campaign_state.json")
BUSY_PATH = os.path.join(PERF, "TPU_BUSY")
PROBE_TIMEOUT = 240
# round-3 windows were as short as ~10 min: a long sleep can consume
# most of one. A probe is one cheap subprocess; keep the cadence tight.
PROBE_SLEEP = 240          # between probes while the tunnel is dead
MIDQUEUE_PROBE_TIMEOUT = 180

# name -> (argv-tail, timeout_s, env-extra)
# Priority order follows VERDICT round-3 "next round" item 1:
# attribution first, then kernel tuning, then A/B, then the headline
# bench + missing ladder rows.
JOBS = [
    ("ablate", [sys.executable, "tools/ablate_step.py"], 4200, {}),
    ("autotune", [sys.executable, "tools/autotune_kernels.py"], 2700, {}),
    ("sweep", [sys.executable, "tools/sweep_gpt_step.py"], 4500, {}),
    # budget > probe retries (720s) + tpu rung (2100s) + its short
    # retry (1260s): the campaign must never kill bench mid-rung and
    # discard measured variants
    ("bench", [sys.executable, "bench.py"], 4500, {}),
    ("ladder_resnet50",
     [sys.executable, "tools/bench_ladder.py", "--run", "resnet50"],
     1500, {}),
    ("ladder_ernie_vil",
     [sys.executable, "tools/bench_ladder.py", "--run", "ernie_vil"],
     1500, {}),
    ("int8_micro", [sys.executable, "tools/bench_int8.py"], 1200, {}),
    # phase 2 (run with --jobs ablate2 after the first queue drains):
    # re-measure the calib + attention micro rows with chained timing
    # (the first run's per-call numbers measured the tunnel RTT), plus
    # the new segment rows and the upstream-kernel A/B
    ("ablate2",
     [sys.executable, "tools/ablate_step.py", "calib", "calib_attn",
      "no_ln", "no_mlp", "jaxflash", "splash"], 3600, {}),
    # the 3D auto-parallel rung (ISSUE 10 / ROADMAP item 5): the
    # planner-driven dp×fsdp×tp sharded step, MULTICHIP-format JSON.
    # Its CPU leg pins the 8-virtual-device platform unconditionally
    # (runs even with the tunnel dead — `--plan3d` shortcuts to it);
    # the --tpu leg is probe-gated inside the tool
    ("plan3d", [sys.executable, "tools/bench_plan3d.py", "--tpu"],
     3000, {}),
    # the sharded-step ablation rows (remat x donation over the plan)
    ("ablate_plan3d",
     [sys.executable, "tools/ablate_step.py", "plan3d", "plan3d_full",
      "plan3d_noremat", "plan3d_nodonate"], 3600, {}),
    # the training MFU observatory rung (ISSUE 12): achieved-vs-
    # roofline per-phase attribution + GSPMD collective audit for the
    # planned train step on the real chip — like --plan3d its CPU leg
    # runs tunnel-free (tools/train_attrib.py pins the 8-virtual-device
    # platform unless --tpu), so this queue entry is the TPU leg
    # single chip -> the plan degrades to dp1 (the attribution +
    # achieved-MFU join itself is the evidence); flagship bench shape
    # so the mfu rows compare with BENCH_window best_tpu
    ("train_attrib",
     [sys.executable, "tools/train_attrib.py", "--tpu",
      "--plans", "dp1_fsdp1_tp1", "--hidden", "1024", "--layers", "24",
      "--vocab", "32768", "--seq", "1024", "--batch", "8",
      "--steps", "10", "--every", "3"], 2700, {}),
    # ISSUE 16 rungs for the next tunnel window:
    # (1) the latency-hiding-collectives A/B — bench_plan3d's overlap
    # legs (plan.overlap -> XLA async-collective/collective-matmul
    # options on the TPU mesh) next to the baseline legs, plus the
    # ablate rows whose plan3d vs plan3d_overlap delta IS the hidden
    # coll_fsdp time
    ("plan3d_overlap",
     [sys.executable, "tools/bench_plan3d.py", "--tpu", "--overlap"],
     4200, {}),
    ("ablate_overlap",
     [sys.executable, "tools/ablate_step.py", "plan3d",
      "plan3d_overlap", "fused_step"], 3600, {}),
    # (2) the fused step kernels (one-pass CE+grad, fused AdamW) —
    # micro A/B in kernel-registry evidence format; --adopt is the ONE
    # evidence-gated writer and refuses on parity miss, <1.03x speedup,
    # or an implausible timing (registry.gate_ms)
    ("fused_step",
     [sys.executable, "tools/bench_fused_step.py", "--tpu", "--adopt"],
     2700, {}),
    # ISSUE 19 rung: the multi-tick decode A/B on the REAL tunnel —
    # the ~70-170 ms per-dispatch RTT is the overhead K amortizes, so
    # the TPU speedup should dwarf the CPU-bench 2.08x. --adopt is the
    # evidence-gated registry writer (parity + >=1.5x + zero recompiles
    # required); single-stream leg + concurrent ITL leg in ONE JSON
    ("multi_tick",
     [sys.executable, "tools/bench_serving.py", "--tpu",
      "--multi-tick", "8", "--requests", "8", "--gen", "64",
      "--adopt"], 2700, {}),
]


def log(msg: str) -> None:
    ts = datetime.datetime.now(datetime.timezone.utc).strftime("%H:%M:%S")
    print(f"[campaign {ts}] {msg}", file=sys.stderr, flush=True)


def load_state() -> dict:
    try:
        with open(STATE_PATH) as f:
            return json.load(f)
    except (OSError, ValueError):
        return {}


def save_state(state: dict) -> None:
    os.makedirs(PERF, exist_ok=True)
    tmp = f"{STATE_PATH}.tmp{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(state, f, indent=1)
    os.replace(tmp, STATE_PATH)


def probe(timeout_s: int = PROBE_TIMEOUT) -> bool:
    """One bounded live-tunnel check in a fresh subprocess (jax caches a
    failed backend in-process, so probing must fork)."""
    code = "import jax; print('PROBE', jax.devices()[0].platform)"
    try:
        res = subprocess.run([sys.executable, "-c", code], cwd=HERE,
                             stdout=subprocess.PIPE,
                             stderr=subprocess.DEVNULL, timeout=timeout_s)
    except subprocess.TimeoutExpired:
        return False
    out = res.stdout.decode()
    return (res.returncode == 0 and "PROBE" in out
            and out.split("PROBE", 1)[1].strip().split()[0]
            in ("tpu", "axon"))


def run_job(name, argv, timeout_s, env_extra, window_dir) -> dict:
    """Run one job with stdout/stderr captured to files; kill the whole
    process group on timeout (bench.py forks its own children)."""
    os.makedirs(window_dir, exist_ok=True)
    out_path = os.path.join(window_dir, f"{name}.out")
    err_path = os.path.join(window_dir, f"{name}.err")
    env = dict(os.environ)
    env.update(env_extra)
    # jobs stamp their artifacts (e.g. perf/autotune.json provenance)
    # with the window they were measured in
    env["PADDLE_TPU_WINDOW"] = os.path.basename(window_dir)
    # share one persistent XLA compile cache across jobs and windows —
    # remote compiles over the tunnel cost minutes; paying them once per
    # graph (not once per job process) stretches every window. Path
    # comes from paddle_tpu.utils.compile_cache (ONE home); jobs that
    # resolve to CPU disable it again via sync_compile_cache_for
    sys.path.insert(0, HERE)
    from paddle_tpu.utils.compile_cache import xla_cache_dir
    env.setdefault("JAX_COMPILATION_CACHE_DIR", xla_cache_dir())
    # LRU cap so a long campaign can't fill the disk with executables
    env.setdefault("JAX_COMPILATION_CACHE_MAX_SIZE", str(2 << 30))
    t0 = time.time()
    with open(out_path, "wb") as fo, open(err_path, "wb") as fe, \
            open(BUSY_PATH, "w") as fb:
        fb.write(f"{name} since {datetime.datetime.now()}\n")
        proc = subprocess.Popen(argv, cwd=HERE, env=env, stdout=fo,
                                stderr=fe, start_new_session=True)
        try:
            rc = proc.wait(timeout=timeout_s)
            timed_out = False
        except subprocess.TimeoutExpired:
            os.killpg(proc.pid, signal.SIGKILL)
            proc.wait()
            rc, timed_out = -9, True
    try:
        os.remove(BUSY_PATH)
    except OSError:
        pass
    dur = round(time.time() - t0, 1)
    with open(out_path, "rb") as f:
        lines = [ln for ln in f.read().decode(errors="replace").splitlines()
                 if ln.startswith("{")]
    recs = []
    for ln in lines:
        try:
            recs.append(json.loads(ln))
        except ValueError:
            pass
    return {"rc": rc, "timed_out": timed_out, "seconds": dur,
            "json_lines": recs, "out": out_path}


def _sweep_step_flops(spec: dict, row: dict) -> float:
    """Approximate train-step arithmetic volume for one sweep row —
    the input the plausibility gate needs. Analytic param count from
    the sweep's model dims (6N flops/token + the attention score/context
    matmul terms, matching bench.py's MFU accounting); precision well
    inside the gate's 2x-roofline..sub-floor window."""
    import sweep_gpt_step as sw
    from bench import train_flops_per_token
    m = {**sw.MODEL, **(spec.get("model") or {})}
    h, L = m["hidden_size"], m["num_layers"]
    seq = int(spec.get("seq", sw.SEQ))
    batch = int(row.get("batch") or spec.get("batch") or sw.BATCH)
    n_params = m["vocab_size"] * h + m["max_seq_len"] * h + 12 * L * h * h
    return train_flops_per_token(n_params, L, h, seq) * batch * seq


def adopt_sweep_winner(json_lines: list, window_ts: str) -> None:
    """Self-executing adoption (round-5): when the sweep lands, persist
    the best tokens/sec variant with its full spec to
    perf/sweep_winner.json AND the kernel-selection registry.
    kernels.flash_attention._attn_impl and the bench race consult these,
    so the measured winner becomes the shipped default without waiting
    for a human to read the window artifact.

    ADOPTION IS EVIDENCE-GATED (ADVICE round-5 item 3): the winning
    row's ms_per_step must sit inside the physical window implied by the
    step's arithmetic volume (registry.gate_ms), so a tunnel-artifact
    timing — implausibly fast clock skew or an RTT-dominated slow row —
    can never ship as the default."""
    try:
        rows = [r for r in json_lines
                if isinstance(r, dict) and r.get("tokens_per_sec")
                and r.get("platform") in ("tpu", "axon")]
        if not rows:
            return
        best = max(rows, key=lambda r: r["tokens_per_sec"])
        sys.path.insert(0, os.path.join(HERE, "tools"))
        from sweep_gpt_step import _specs
        spec = next((s for s in _specs() if s["name"] == best["name"]),
                    {})
        from paddle_tpu.kernels import registry
        flops = _sweep_step_flops(spec, best)
        reason = registry.gate_ms(float(best["ms_per_step"]), flops=flops)
        if reason:
            log(f"sweep winner {best['name']} REJECTED by the "
                f"plausibility gate ({reason}); NOT adopting")
            return
        doc = {
            "name": best["name"],
            "tokens_per_sec": best["tokens_per_sec"],
            "ms_per_step": best["ms_per_step"],
            "batch": best.get("batch"),
            "env": spec.get("env", {}),
            "remat": spec.get("remat"),
            "policy": spec.get("policy"),
            "window": window_ts,
            "gate": {"flops": flops, "passed": True},
        }
        path = os.path.join(PERF, "sweep_winner.json")
        tmp = f"{path}.tmp{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(doc, f, indent=1)
        os.replace(tmp, path)
        log(f"adopted sweep winner {best['name']} "
            f"({best['tokens_per_sec']} tok/s) -> perf/sweep_winner.json")
        # persist the attention impl into the registry too (the durable
        # per-backend-class table consulted when no fresh sweep file is
        # around); adopt() re-runs the same gate before writing
        from paddle_tpu.kernels.flash_attention import impl_from_winner_env
        impl = impl_from_winner_env(spec.get("env", {}))
        if impl:
            seq = int(spec.get("seq", 0) or 1024)
            err = registry.adopt(
                "attention", impl, ms=float(best["ms_per_step"]),
                flops=flops, backend="tpu",
                bucket=registry.seq_bucket(seq),
                source=f"sweep {best['name']} "
                       f"({best['tokens_per_sec']} tok/s)",
                window=window_ts,
                path=os.path.join(PERF, "kernel_registry.json"))
            log(f"registry adoption: attention::tpu -> {impl}"
                + (f" REJECTED ({err})" if err else ""))
    except Exception as e:
        log(f"sweep winner adoption failed (non-fatal): {e!r}")


def append_window_artifact(window_ts: str, job: str, recs: list) -> None:
    """Repo-root machine-readable record of everything measured in this
    window — bench/judge artifacts must not depend on the tunnel staying
    alive (VERDICT weak #4)."""
    path = os.path.join(HERE, f"BENCH_window_{window_ts}.json")
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError):
        doc = {"window_utc": window_ts, "results": []}
    doc["results"].extend(
        {"job": job, "measured_utc":
         datetime.datetime.now(datetime.timezone.utc).isoformat(
             timespec="seconds"), **r} for r in recs)
    tmp = f"{path}.tmp{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(doc, f, indent=1)
    os.replace(tmp, path)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--jobs", default=None,
                    help="comma-separated subset/order override")
    ap.add_argument("--once", action="store_true",
                    help="single probe; exit 3 if tunnel dead")
    ap.add_argument("--force-rerun", action="store_true",
                    help="ignore done-markers in campaign_state.json")
    ap.add_argument("--plan3d", action="store_true",
                    help="run the plan3d rung NOW (no tunnel gate: its "
                         "CPU leg pins the 8-virtual-device platform "
                         "unconditionally; the TPU leg stays "
                         "probe-gated inside the tool)")
    ap.add_argument("--plan4d", action="store_true",
                    help="run the plan3d rung WITH the cpu8_pp 4D leg "
                         "(dp2×tp2×pp2, 1F1B microbatching — ISSUE 15; "
                         "same no-tunnel-gate semantics: the CPU legs "
                         "pin the 8-virtual-device platform "
                         "unconditionally)")
    args = ap.parse_args()

    if args.plan3d or args.plan4d:
        # no probe loop: the rung must produce its CPU-mesh evidence
        # even with the tunnel dead — TPU execution is gated inside
        # bench_plan3d.py
        window_ts = datetime.datetime.now(
            datetime.timezone.utc).strftime("%Y%m%dT%H%M%SZ")
        window_dir = os.path.join(PERF, f"window_{window_ts}")
        job = next(j for j in JOBS if j[0] == "plan3d")
        name, argv, timeout_s, env_extra = job
        if args.plan4d:
            name, argv = "plan4d", list(argv) + ["--pp"]
        log(f"--{name}: running (timeout {timeout_s}s)")
        res = run_job(name, argv, timeout_s, env_extra, window_dir)
        log(f"plan3d: rc={res['rc']} {res['seconds']}s, "
            f"{len(res['json_lines'])} JSON records")
        if res["json_lines"]:
            append_window_artifact(window_ts, name, res["json_lines"])
            for rec in res["json_lines"]:
                print(json.dumps(rec), flush=True)
        sys.exit(0 if res["rc"] == 0 and res["json_lines"] else 1)

    queue = JOBS
    if args.jobs:
        want = args.jobs.split(",")
        by_name = {j[0]: j for j in JOBS}
        unknown = [w for w in want if w not in by_name]
        if unknown:
            ap.error(f"unknown job(s) {unknown}; known: "
                     f"{sorted(by_name)}")
        queue = [by_name[w] for w in want]

    state = load_state()
    pending = [j for j in queue
               if args.force_rerun or state.get(j[0], {}).get("status")
               != "done"]
    if not pending:
        log("queue already drained; nothing to do")
        return
    log(f"queue: {[j[0] for j in pending]}")

    while pending:
        if not probe():
            if args.once:
                log("tunnel dead (--once); exiting 3")
                sys.exit(3)
            log(f"tunnel dead; sleeping {PROBE_SLEEP}s "
                f"({len(pending)} jobs pending)")
            time.sleep(PROBE_SLEEP)
            continue
        window_ts = datetime.datetime.now(
            datetime.timezone.utc).strftime("%Y%m%dT%H%M%SZ")
        window_dir = os.path.join(PERF, f"window_{window_ts}")
        log(f"TUNNEL ALIVE — window {window_ts}, draining queue")
        dead_probes = 0
        while pending and dead_probes < 2:
            name, argv, timeout_s, env_extra = pending[0]
            log(f"job {name} (timeout {timeout_s}s)")
            res = run_job(name, argv, timeout_s, env_extra, window_dir)
            n = len(res["json_lines"])
            log(f"job {name}: rc={res['rc']} {res['seconds']}s, "
                f"{n} JSON records"
                + (" [TIMEOUT, salvaged partial]" if res["timed_out"]
                   else ""))
            if res["json_lines"]:
                append_window_artifact(window_ts, name, res["json_lines"])
            prev_fails = state.get(name, {}).get("fails", 0)
            state[name] = {
                "status": ("done" if res["rc"] == 0 and n else
                           "partial" if n else "failed"),
                "window": window_ts, "rc": res["rc"],
                "seconds": res["seconds"], "records": n,
                "fails": prev_fails,      # carried; bumped on live failure
            }
            save_state(state)
            if res["rc"] == 0 and n:
                if name == "sweep":
                    adopt_sweep_winner(res["json_lines"], window_ts)
                pending.pop(0)
                dead_probes = 0
                continue
            # job died: distinguish "tunnel dropped" from "job broken"
            if probe(MIDQUEUE_PROBE_TIMEOUT):
                log(f"tunnel still alive; {name} itself failed — "
                    f"moving it to the back of the queue")
                pending.append(pending.pop(0))
                dead_probes = 0
                # a job that failed twice in live windows is broken, not
                # unlucky: drop it so it can't starve the queue
                fails = state[name].get("fails", 0) + 1
                state[name]["fails"] = fails
                if fails >= 2:
                    log(f"job {name} failed {fails}x live; dropping")
                    pending = [j for j in pending if j[0] != name]
                save_state(state)
            else:
                dead_probes += 1
                log(f"tunnel no longer answers (strike {dead_probes}/2)")
        log(f"window {window_ts} closed; "
            f"{len(pending)} jobs still pending")
        if args.once:
            break
    log("campaign complete" if not pending else "campaign exiting")


if __name__ == "__main__":
    main()
