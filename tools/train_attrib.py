"""Roofline attribution for the planned train step: measured ms/step vs
the cost-model train ledger, per phase, per plan — plus the GSPMD
collective audit of the step that actually compiled.

The training-side sibling of tools/serving_attrib.py, joining three
sources per dp×fsdp×tp plan:
- measured ms/step from the batched telemetry stream
  (profiler/telemetry with the MFU_FIELDS `tokens` extension — step
  timing comes from flush-to-flush wall deltas, the first window
  excluded as the compile window, exactly telemetry_report's rule),
  and the `train.mfu` gauge the flush computes;
- the analytical per-phase price of that step
  (cost_model.train_step_ledger: fwd matmuls/attention, bwd at 2x,
  remat recompute, optimizer, head/loss, per-axis collective phases
  against ChipSpec.ici_bw) rooflined by cost_model.roofline_attribution
  (predicted step ms, bound phase, peak MFU per plan);
- the HLO collective audit (profiler/hlo_audit): which collectives
  GSPMD REALLY inserted vs the plan's expected schedule — surprise
  resharding collectives are named findings, not a slow step.

On the CPU rung the achieved fraction calibrates the harness (the
roofline prices a TPU chip); run with --tpu on a live window for the
real MFU rows. Each measured row is also appended to the telemetry
JSONL as a {"kind": "train_attrib"} record so telemetry_report's
`train_attrib` block can replay the join offline.

Usage:
  python tools/train_attrib.py                     # dp2x fsdp2x tp2 + fsdp8
  python tools/train_attrib.py --plans dp2_fsdp2_tp2,dp4_tp2,fsdp8
  python tools/train_attrib.py --pretty --steps 16
  python tools/train_attrib.py --from-jsonl RUN.jsonl --plans fsdp8
"""
from __future__ import annotations

import argparse
import json
import os
import re
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

# CPU (8 virtual devices — the mesh the plans need) unconditionally:
# the axon tunnel flaps and ANY backend init then hangs (CLAUDE.md
# trap) — pass --tpu to run on the default backend. Script-mode only:
# importers (tools/ablate_step.py's train_attrib variant, tests) own
# their backend and must not have it re-pinned at import time.
from paddle_tpu.device import pin_cpu            # noqa: E402
if __name__ == "__main__" and "--tpu" not in sys.argv:
    pin_cpu(8)

import numpy as np                               # noqa: E402
import jax                                       # noqa: E402
import jax.numpy as jnp                          # noqa: E402


def _log(msg):
    print(f"[train_attrib] {msg}", file=sys.stderr, flush=True)


def parse_plan_name(name: str) -> dict:
    """'dp2_fsdp2_tp2' / 'dp4_tp2' / 'fsdp8' -> explicit degrees.
    'pp'/'mb' tokens select the pipelined step ('dp2_tp2_pp2_mb4');
    an 'overlap' token turns on the latency-hiding collective schedule
    (docs/parallel_training.md §Collective overlap)."""
    deg = {"dp": 1, "fsdp": 1, "tp": 1}
    for axis, n in re.findall(r"(dp|fsdp|tp|pp|mb)(\d+)", name):
        deg[axis] = int(n)
    if deg.pop("mb", None):
        deg["microbatches"] = int(re.search(r"mb(\d+)", name).group(1))
    if deg.get("pp", 1) == 1:
        deg.pop("pp", None)
    if "overlap" in name:
        deg["overlap"] = True
    return deg


def build_cfg(args):
    from paddle_tpu.models.gpt import GPTConfig
    return GPTConfig(vocab_size=args.vocab, hidden_size=args.hidden,
                     num_layers=args.layers,
                     num_heads=max(args.hidden // 32, 1),
                     max_seq_len=2 * args.seq, dtype=jnp.float32,
                     remat=False, sequence_parallel=False)


def attrib_row(summary: dict, ledger: dict, roof: dict,
               audit: dict = None, plan_name: str = "") -> dict:
    """Join a telemetry_report.summarize() doc with a train ledger's
    roofline (and optionally an HLO audit) into one achieved-vs-
    roofline row — the serving_attrib row format, train flavored.
    Importable so recorded JSONLs can be re-joined offline
    (tests/test_train_observability.py)."""
    st = summary.get("step_time") or {}
    measured_ms = st.get("p50_ms")
    roof_ms = roof["roofline_s"] * 1e3
    row = {
        "plan": plan_name or (ledger["config"]["plan"]
                              if "config" in ledger else ""),
        "steps": st.get("steps"),
        "measured_ms_per_step_p50": measured_ms,
        "compile_window_ms_per_step":
            summary.get("compile_window_ms_per_step"),
        "roofline_ms_per_step": round(roof_ms, 6),
        "achieved_vs_roofline": round(roof_ms / measured_ms, 6)
        if measured_ms else None,
        "peak_mfu": roof.get("peak_mfu"),
        "achieved_mfu": (summary.get("mfu") or {}).get("mfu"),
        "tokens_per_s": (summary.get("mfu") or {}).get("tokens_per_s"),
        "model_flops_per_step": round(ledger.get("model_flops", 0)),
        "phases": {
            p: {"share": v["share"], "bound": v["bound"],
                "flops": round(v["flops"]), "bytes": round(v["bytes"])}
            for p, v in roof["per_phase"].items()},
    }
    if audit is not None:
        row["audit"] = {
            "counts": audit["counts"],
            "findings": [
                {"kind": f["kind"], "op": f["op"], "axes": f["axes"],
                 "count": f["count"], "bytes": f["bytes"]}
                for f in audit["findings"]],
            "compile_ms": audit["compile_ms"],
        }
    return row


def measure_plan(name, cfg, args, peak_flops, hbm_bw, ici_bw):
    """One plan: plan, ledger, instrumented telemetry run, report join,
    HLO audit."""
    from paddle_tpu.cost_model import (train_step_ledger,
                                       roofline_attribution)
    from paddle_tpu.models.gpt import (init_gpt_params, init_opt_state,
                                       train_step)
    from paddle_tpu.parallel.planner import plan_train, ChipSpec
    from paddle_tpu.profiler import hlo_audit
    from paddle_tpu.profiler.telemetry import (TelemetryPipeline,
                                               instrument_train_step,
                                               MFU_FIELDS)
    from telemetry_report import summarize

    deg = parse_plan_name(name)
    if getattr(args, "overlap", False):
        deg["overlap"] = True
    n_devices = (deg["dp"] * deg["fsdp"] * deg["tp"]
                 * deg.get("pp", 1))
    plan = plan_train(cfg, n_devices, args.batch, **deg)
    mesh = plan.build_mesh()
    ledger = train_step_ledger(cfg, plan=plan, global_batch=args.batch,
                               seq=args.seq)
    roof = roofline_attribution(ledger, peak_flops=peak_flops,
                                hbm_bw=hbm_bw, ici_bw=ici_bw)
    chip_peak = peak_flops or ChipSpec().peak_flops
    path = f"{args.jsonl_prefix}.{name}.jsonl"
    if os.path.exists(path):
        os.remove(path)
    tele = TelemetryPipeline(
        path, every=args.every, fields=MFU_FIELDS,
        meta={"samples_per_step": args.batch, "plan": name},
        flops_per_token=ledger["model_flops"] / ledger["tokens"],
        peak_flops=chip_peak * n_devices)
    step = instrument_train_step(train_step, tele, cfg=cfg, lr=1e-3,
                                 mesh=mesh, plan=plan)
    params = init_gpt_params(cfg, jax.random.PRNGKey(0))
    opt = init_opt_state(params)
    toks = jnp.asarray(np.random.RandomState(0).randint(
        0, cfg.vocab_size, (args.batch, args.seq + 1)), jnp.int32)
    tstate = tele.device_init()
    t0 = time.perf_counter()
    for i in range(args.steps):
        loss, params, opt, tstate = step(params, opt, toks, tstate)
        tstate = tele.tick(i, tstate)
    float(loss)
    _log(f"{name}: {args.steps} steps in "
         f"{time.perf_counter() - t0:.1f}s, traces={step.trace_count}")
    tele.close()
    audit = hlo_audit.audit_train_step(cfg, plan, args.batch,
                                       seq=args.seq)
    row = attrib_row(summarize(path), ledger, roof, audit=audit,
                     plan_name=plan.name)
    # embed the join in the stream for offline replay
    # (telemetry_report's train_attrib block)
    with open(path, "a") as f:
        f.write(json.dumps({"kind": "train_attrib", **row}) + "\n")
    return row


def join_jsonl(path, name, cfg, args, peak_flops, hbm_bw, ici_bw):
    """--from-jsonl: re-join a recorded telemetry stream with the
    ledger (no execution, no audit)."""
    from paddle_tpu.cost_model import (train_step_ledger,
                                       roofline_attribution)
    from telemetry_report import summarize
    ledger = train_step_ledger(cfg, plan=parse_plan_name(name),
                               global_batch=args.batch, seq=args.seq)
    roof = roofline_attribution(ledger, peak_flops=peak_flops,
                                hbm_bw=hbm_bw, ici_bw=ici_bw)
    return attrib_row(summarize(path), ledger, roof, plan_name=name)


def render_table(rows) -> str:
    """The human-readable achieved-vs-roofline table."""
    lines = []
    hdr = (f"{'plan':<16} {'ms/step':>9} {'roofline':>10} "
           f"{'achieved':>9} {'peakMFU':>8} {'findings':>8}  "
           f"top phases (bound)")
    lines.append(hdr)
    lines.append("-" * len(hdr))
    for r in rows:
        shares = "  ".join(
            f"{p}={v['share']:.0%}({v['bound'][0]})"
            for p, v in sorted(r["phases"].items(),
                               key=lambda kv: -kv[1]["share"])
            if v["share"] >= 0.02)
        nf = len((r.get("audit") or {}).get("findings", []))
        meas = r["measured_ms_per_step_p50"]
        ach = r["achieved_vs_roofline"]
        lines.append(
            f"{r['plan']:<16} "
            f"{meas if meas is not None else float('nan'):>9.3f} "
            f"{r['roofline_ms_per_step']:>10.4f} "
            f"{ach if ach is not None else float('nan'):>9.2%} "
            f"{r['peak_mfu'] or 0:>8.1%} {nf:>8}  {shares}")
    return "\n".join(lines)


def load_rows(path) -> list:
    """All train_attrib rows a JSONL file carries — either the main()
    stdout doc ({"metric": "train_roofline_attribution", "plans": [..]})
    or a telemetry stream with embedded {"kind": "train_attrib"}
    records (measure_plan appends one per run)."""
    rows = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                d = json.loads(line)
            except ValueError:
                continue
            if d.get("metric") == "train_roofline_attribution":
                rows.extend(d.get("plans") or [])
            elif d.get("kind") == "train_attrib":
                rows.append(d)
    return rows


def compare_rows(before: list, after: list) -> list:
    """Join two row sets by plan name into delta rows: measured
    ms/step, achieved MFU, and per-phase roofline share deltas
    (after − before). The before/after evidence format for the overlap
    and fused-kernel campaigns (BASELINE.md §MFU campaign)."""
    out = []
    bmap = {r.get("plan"): r for r in before}
    for a in after:
        b = bmap.get(a.get("plan"))
        if b is None:
            continue

        def _d(key):
            av, bv = a.get(key), b.get(key)
            return (round(av - bv, 6)
                    if av is not None and bv is not None else None)
        phases = sorted(set(b.get("phases") or {})
                        | set(a.get("phases") or {}))
        share = {
            p: round(((a.get("phases") or {}).get(p) or {})
                     .get("share", 0.0)
                     - ((b.get("phases") or {}).get(p) or {})
                     .get("share", 0.0), 6)
            for p in phases}
        out.append({
            "plan": a.get("plan"),
            "measured_ms_before": b.get("measured_ms_per_step_p50"),
            "measured_ms_after": a.get("measured_ms_per_step_p50"),
            "measured_ms_delta": _d("measured_ms_per_step_p50"),
            "achieved_mfu_before": (b.get("achieved_mfu")),
            "achieved_mfu_after": (a.get("achieved_mfu")),
            "achieved_mfu_delta": _d("achieved_mfu"),
            "findings_before": len((b.get("audit") or {})
                                   .get("findings", [])),
            "findings_after": len((a.get("audit") or {})
                                  .get("findings", [])),
            "phase_share_delta": share,
        })
    return out


def render_compare(cmp_rows) -> str:
    """The human-readable before/after delta table."""
    lines = []
    hdr = (f"{'plan':<18} {'ms b':>9} {'ms a':>9} {'Δms':>8} "
           f"{'MFU b':>7} {'MFU a':>7} {'ΔMFU':>7}  "
           f"phase-share deltas (|Δ| >= 1%)")
    lines.append(hdr)
    lines.append("-" * len(hdr))

    def fm(v, spec, dash="      --"):
        return format(v, spec) if v is not None else dash
    for r in cmp_rows:
        shares = "  ".join(
            f"{p}{d:+.0%}" for p, d in sorted(
                r["phase_share_delta"].items(), key=lambda kv: kv[1])
            if abs(d) >= 0.01)
        lines.append(
            f"{r['plan']:<18} {fm(r['measured_ms_before'], '>9.3f')} "
            f"{fm(r['measured_ms_after'], '>9.3f')} "
            f"{fm(r['measured_ms_delta'], '>+8.3f')} "
            f"{fm(r['achieved_mfu_before'], '>7.2%')} "
            f"{fm(r['achieved_mfu_after'], '>7.2%')} "
            f"{fm(r['achieved_mfu_delta'], '>+7.2%')}  {shares}")
    return "\n".join(lines)


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--plans", default="dp2_fsdp2_tp2,fsdp8",
                    help="comma-separated plan names (dpN_fsdpN_tpN)")
    ap.add_argument("--steps", type=int, default=12)
    ap.add_argument("--every", type=int, default=4,
                    help="telemetry flush cadence (>=2 windows needed "
                         "for post-compile step timing)")
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--seq", type=int, default=32)
    ap.add_argument("--hidden", type=int, default=128)
    ap.add_argument("--layers", type=int, default=2)
    ap.add_argument("--vocab", type=int, default=512)
    ap.add_argument("--jsonl-prefix", default="/tmp/train_attrib",
                    help="per-plan telemetry JSONL path prefix")
    ap.add_argument("--from-jsonl", default=None,
                    help="join THIS recorded telemetry JSONL with the "
                         "ledger instead of running (uses the first "
                         "--plans name)")
    ap.add_argument("--tpu", action="store_true",
                    help="run on the default (TPU) backend")
    ap.add_argument("--peak-flops", type=float, default=None,
                    help="per-chip roofline FLOP/s (default: "
                         "planner.ChipSpec)")
    ap.add_argument("--hbm-bw", type=float, default=None)
    ap.add_argument("--ici-bw", type=float, default=None)
    ap.add_argument("--pretty", action="store_true")
    ap.add_argument("--overlap", action="store_true",
                    help="plan every --plans entry with the "
                         "latency-hiding collective overlap knob on")
    ap.add_argument("--compare", nargs=2, metavar=("BEFORE", "AFTER"),
                    default=None,
                    help="diff two recorded train_attrib JSONLs "
                         "(stdout docs or telemetry streams) instead "
                         "of running; prints per-plan ms/MFU/"
                         "phase-share deltas")
    args = ap.parse_args()

    if args.compare:
        cmp_rows = compare_rows(load_rows(args.compare[0]),
                                load_rows(args.compare[1]))
        print(json.dumps({"metric": "train_attrib_compare",
                          "before": args.compare[0],
                          "after": args.compare[1],
                          "plans": cmp_rows}), flush=True)
        print(render_compare(cmp_rows), flush=True)
        return 0

    cfg = build_cfg(args)
    names = [n for n in args.plans.split(",") if n]
    rows = []
    if args.from_jsonl:
        rows.append(join_jsonl(args.from_jsonl, names[0], cfg, args,
                               args.peak_flops, args.hbm_bw,
                               args.ici_bw))
    else:
        for name in names:
            _log(f"measuring {name} ...")
            rows.append(measure_plan(name, cfg, args, args.peak_flops,
                                     args.hbm_bw, args.ici_bw))
    doc = {"metric": "train_roofline_attribution",
           "backend": jax.devices()[0].platform,
           "model": f"{args.layers}Lx{args.hidden}d",
           "batch": args.batch, "seq": args.seq, "steps": args.steps,
           "plans": rows}
    print(json.dumps(doc), flush=True)
    if args.pretty:
        print(render_table(rows), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
