"""Ablation bench: where do the GPT train-step milliseconds go?

Runs on the real TPU. Each variant rebuilds + jits the step and measures
steady-state ms/step; differences between variants attribute time to the
ablated component. Also calibrates the achievable matmul rate (bf16 and
fp32) so MFU targets are grounded in what the chip actually delivers
through the tunnel, not the datasheet.

Usage: python tools/ablate_step.py [variant ...]   (default: all)
Output: one JSON line per variant on stdout; diagnostics on stderr.
"""
from __future__ import annotations

import functools
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(1, os.path.dirname(os.path.abspath(__file__)))

import jax
import jax.numpy as jnp
import numpy as np


def log(m):
    print(f"[ablate] {m}", file=sys.stderr, flush=True)


def emit(name, ms, extra=None):
    rec = {"variant": name, "ms": round(ms, 2)}
    if extra:
        rec.update(extra)
    print(json.dumps(rec), flush=True)


from bench_util import (chained_ms, force as _force,  # noqa: E402
                        mix_grads, timeit)


# ------------------------------------------------------------ calibration
def calib_matmul():
    """Achievable dense matmul rate, bf16 and f32 — the real peak.

    The scan carries a square activation through back-to-back matmuls
    with NO reshaping/slicing between them (an earlier version sliced the
    product back to [M,K] each iteration, which inserted a 64MB copy per
    matmul and understated the peak by ~2x). Weights are 1/D-filled so
    each hop is a row-mean: magnitudes are hop-count-invariant and the
    long chains below can't overflow."""
    # inner chain length keeps ONE dispatch's device time well above the
    # tunnel RTT — the first run of this calib (length=16, 10 dispatches)
    # measured 2.9 TF/s for work the model path drives at ~40 TF/s, i.e.
    # it measured the tunnel
    for n, dt in (("bf16", jnp.bfloat16), ("f32", jnp.float32)):
        D = 4096
        x = jnp.full((D, D), 0.5, dt)
        w = jnp.full((D, D), 1.0 / D, dt)
        fl = 2.0 * D * D * D
        length = 128 if dt == jnp.bfloat16 else 32

        @jax.jit
        def mm(x, w):
            def body(h, _):
                return (h @ w).astype(dt), None
            h, _ = jax.lax.scan(body, x, None, length=length)
            return h

        ms = timeit(mm, x, w, iters=3)
        tf = length * fl / (ms * 1e-3) / 1e12
        emit(f"calib_matmul_{n}", ms, {"tflops": round(tf, 1)})

    # the model's actual hot shape: [B*S, D] @ [D, 4D] (MLP up-proj)
    M, K, N = 8192, 1024, 4096
    # 1/K and 1/N fills make each (h@b)@c round trip a pure mean:
    # magnitudes stay at 0.5 across the whole chain
    a = jnp.full((M, K), 0.5, jnp.bfloat16)
    b = jnp.full((K, N), 1.0 / K, jnp.bfloat16)
    c = jnp.full((N, K), 1.0 / N, jnp.bfloat16)

    @jax.jit
    def mlp(a, b, c):
        def body(h, _):
            return ((h @ b) @ c).astype(jnp.bfloat16), None
        h, _ = jax.lax.scan(body, a, None, length=128)
        return h

    ms = timeit(mlp, a, b, c, iters=3)
    tf = 128 * 2 * (2.0 * M * K * N) / (ms * 1e-3) / 1e12
    emit("calib_matmul_mlp_shape", ms, {"tflops": round(tf, 1)})


def calib_attention():
    """Flash fwd kernel alone vs the XLA blockwise path, fwd and fwd+bwd."""
    from paddle_tpu.kernels import flash_attention as fa
    from paddle_tpu.kernels.pallas_attention import mha_fwd
    B, S, H, D = 8, 1024, 16, 64
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    q = jax.random.normal(ks[0], (B, S, H, D), jnp.bfloat16)
    k = jax.random.normal(ks[1], (B, S, H, D), jnp.bfloat16)
    v = jax.random.normal(ks[2], (B, S, H, D), jnp.bfloat16)

    # chained (see bench_util.chained_ms): single-kernel dispatches sit
    # below the tunnel RTT, so the first run of these rows ranked the
    # backends by RTT noise rather than device time
    emit("attn_pallas_fwd", chained_ms(
        lambda qc: mha_fwd(qc, k, v, causal=True)[0].astype(q.dtype),
        q, length=32, iters=3))

    emit("attn_xla_fwd", chained_ms(
        lambda qc: fa._blockwise_attention_lse(
            qc, k, v, True)[0].astype(q.dtype),
        q, length=32, iters=3))

    def grad_q(loss):
        gfn = jax.grad(loss, argnums=(0, 1, 2))
        return lambda qc: mix_grads(gfn(qc, k, v), q.dtype)

    def loss_pallas(q, k, v):
        return jnp.sum(fa._flash_mha(q, k, v, True).astype(jnp.float32))

    # main() snapshots/restores the whole env around each variant, so
    # plain sets are safe here
    os.environ["PADDLE_TPU_DISABLE_PALLAS_BWD"] = "1"
    emit("attn_fwd_jaxbwd",
         chained_ms(grad_q(loss_pallas), q, length=16, iters=3))
    os.environ["PADDLE_TPU_DISABLE_PALLAS_BWD"] = "0"
    emit("attn_fwd_pallasbwd",
         chained_ms(grad_q(lambda q, k, v: loss_pallas(q, k, v) * 1.0),
                    q, length=16, iters=3))


# ------------------------------------------------------------ step variants
def build(cfg_kw, batch=8, seq=1024):
    from paddle_tpu.models.gpt import (GPTConfig, init_gpt_params,
                                       init_opt_state)
    kw = dict(vocab_size=32768, hidden_size=1024, num_layers=24,
              num_heads=16, max_seq_len=1024, dtype=jnp.bfloat16,
              sequence_parallel=False)
    kw.update(cfg_kw)
    cfg = GPTConfig(**kw)
    params = init_gpt_params(cfg, jax.random.PRNGKey(0))
    opt = init_opt_state(params)
    toks = jax.random.randint(jax.random.PRNGKey(1), (batch, seq + 1), 0,
                              cfg.vocab_size)
    return cfg, params, opt, toks


def step_ms(cfg, params, opt, toks, iters=10):
    from paddle_tpu.models.gpt import train_step
    from paddle_tpu.models.facade import make_train_step
    step = make_train_step(train_step, cfg=cfg, lr=1e-4)
    t0 = time.perf_counter()
    loss, params, opt = step(params, opt, toks)
    float(loss)
    log(f"  compile+first {time.perf_counter() - t0:.1f}s")
    t0 = time.perf_counter()
    for _ in range(iters):
        loss, params, opt = step(params, opt, toks)
    float(loss)
    return (time.perf_counter() - t0) / iters * 1e3


def v_baseline():
    os.environ["PADDLE_TPU_DISABLE_PALLAS_BWD"] = "1"
    cfg, p, o, t = build(dict(remat=True, remat_policy="full"))
    emit("full_remat_pallasfwd_jaxbwd_b8", step_ms(cfg, p, o, t))


def v_dots():
    os.environ["PADDLE_TPU_DISABLE_PALLAS_BWD"] = "1"
    cfg, p, o, t = build(dict(remat=True, remat_policy="dots"))
    emit("dots_remat_b8", step_ms(cfg, p, o, t))


def v_dots_flash():
    """dots + saved flash outputs: no attention recompute in backward."""
    os.environ["PADDLE_TPU_DISABLE_PALLAS_BWD"] = "1"
    cfg, p, o, t = build(dict(remat=True, remat_policy="dots_flash"))
    emit("dots_flash_remat_b8", step_ms(cfg, p, o, t))


def v_noremat_b4():
    os.environ["PADDLE_TPU_DISABLE_PALLAS_BWD"] = "1"
    cfg, p, o, t = build(dict(remat=False), batch=4)
    emit("noremat_b4", step_ms(cfg, p, o, t))


def v_xla_attn():
    os.environ["PADDLE_TPU_DISABLE_PALLAS"] = "1"
    cfg, p, o, t = build(dict(remat=True, remat_policy="full"))
    emit("xla_attn_b8", step_ms(cfg, p, o, t))


def v_no_attn():
    """Attention replaced by identity: isolates the whole attention cost."""
    from paddle_tpu.kernels import flash_attention as fa
    orig = fa._flash_mha
    fa._flash_mha = lambda q, k, v, causal, kv_len=None: v
    try:
        cfg, p, o, t = build(dict(remat=True, remat_policy="full"))
        emit("no_attn_b8", step_ms(cfg, p, o, t))
    finally:
        fa._flash_mha = orig


def v_fwd_only():
    os.environ["PADDLE_TPU_DISABLE_PALLAS_BWD"] = "1"
    from paddle_tpu.models.gpt import gpt_loss
    cfg, p, o, t = build(dict(remat=False))
    f = jax.jit(functools.partial(gpt_loss, cfg=cfg))
    t0 = time.perf_counter()
    float(f(p, t))
    log(f"  compile+first {time.perf_counter() - t0:.1f}s")
    t0 = time.perf_counter()
    for _ in range(10):
        out = f(p, t)
    float(out)
    emit("fwd_only_noremat_b8", (time.perf_counter() - t0) / 10 * 1e3)


def v_no_head():
    """Loss = mean of final hidden state: isolates LM head + softmax cost."""
    from paddle_tpu.models import gpt as G
    cfg, p, o, t = build(dict(remat=True, remat_policy="full"))

    def loss_nohead(params, batch, cfg):
        inp = batch[:, :-1]
        B, S = inp.shape
        x = jnp.take(params["wte"], inp, axis=0).astype(cfg.dtype)
        x = x + params["wpe"][:S][None].astype(cfg.dtype)
        stacked = {k: params[k] for k in G._BLOCK_KEYS_DENSE if k in params}
        x, _aux = G._apply_stack(stacked, x, cfg)
        x = G._ln(x, params["ln_f_scale"], params["ln_f_bias"],
                  cfg.layer_norm_eps)
        return jnp.mean(x.astype(jnp.float32))

    orig = G.gpt_loss
    G.gpt_loss = loss_nohead
    try:
        emit("no_head_b8", step_ms(cfg, p, o, t))
    finally:
        G.gpt_loss = orig


def v_no_ln():
    """LayerNorm replaced by identity: isolates LN (f32 stats) cost.
    Same backward impl as v_baseline, so the delta is pure LN."""
    os.environ["PADDLE_TPU_DISABLE_PALLAS_BWD"] = "1"
    from paddle_tpu.models import gpt as G
    orig = G._ln
    G._ln = lambda x, scale, bias, eps: x
    try:
        cfg, p, o, t = build(dict(remat=True, remat_policy="full"))
        emit("no_ln_b8", step_ms(cfg, p, o, t))
    finally:
        G._ln = orig


def v_no_mlp():
    """Dense FFN replaced by identity: isolates the MLP cost.
    Same backward impl as v_baseline, so the delta is pure MLP."""
    os.environ["PADDLE_TPU_DISABLE_PALLAS_BWD"] = "1"
    from paddle_tpu.models import gpt as G
    orig = G._dense_ffn
    G._dense_ffn = lambda x, *a: x
    try:
        cfg, p, o, t = build(dict(remat=True, remat_policy="full"))
        emit("no_mlp_b8", step_ms(cfg, p, o, t))
    finally:
        G._dense_ffn = orig


def v_jaxflash():
    """Upstream jax.experimental TPU flash kernel as the attention impl.
    Numerics first: the step timing means nothing if the upstream kernel
    disagrees with the dense oracle on this backend."""
    _impl_variant("jax_flash", "jaxflash_dotsflash_b8")


def _impl_variant(impl, row_name):
    """Parity-check `impl` against the dense oracle on-device, then time
    the full step with it (dots_flash remat so the kernel's forward is
    saved, not recomputed)."""
    from paddle_tpu.kernels import flash_attention as fa
    fn = {"jax_flash": fa._jax_flash_mha, "splash": fa._splash_mha}[impl]
    ks = jax.random.split(jax.random.PRNGKey(7), 3)
    q = jax.random.normal(ks[0], (2, 512, 4, 64), jnp.bfloat16)
    k = jax.random.normal(ks[1], (2, 512, 4, 64), jnp.bfloat16)
    v = jax.random.normal(ks[2], (2, 512, 4, 64), jnp.bfloat16)
    got = np.asarray(jax.jit(fn, static_argnums=3)(q, k, v, True),
                     np.float32)
    want = np.asarray(fa._dense_reference(q, k, v, True), np.float32)
    err = float(np.max(np.abs(got - want)))
    if err > 0.05:
        emit(f"{row_name}_parity", -1.0, {"max_abs_err": err})
        return
    os.environ["PADDLE_TPU_ATTN_IMPL"] = impl
    cfg, p, o, t = build(dict(remat=True, remat_policy="dots_flash"))
    emit(row_name, step_ms(cfg, p, o, t),
         {"parity_max_abs_err": round(err, 5)})


def v_splash():
    """Upstream splash-attention kernel as the attention impl."""
    _impl_variant("splash", "splash_dotsflash_b8")


# ------------------------------------------------- 3D sharded-step rows
def _step_flops(cfg, params, batch, seq):
    """Step arithmetic volume (bench.train_flops_per_token — ONE home
    for the MFU accounting, real param count) — the evidence field the
    kernel-registry plausibility gate (registry.gate_ms) needs, so a
    tunnel-artifact plan3d timing can be rejected like any other row."""
    from bench import train_flops_per_token
    n_params = sum(int(v.size) for v in params.values())
    return train_flops_per_token(n_params, cfg.num_layers,
                                 cfg.hidden_size, seq) * batch * seq


def _plan3d_variant(row_name, cfg_kw, donate=True, batch=8, seq=1024,
                    overlap=False):
    """One sharded-step ablation row: plan the 3D dp×fsdp×tp assignment
    for THIS backend's device count (on one TPU chip the plan degrades
    to dp1 — the row then isolates the pin/donate overhead itself),
    build the planner-driven GSPMD step with the given remat policy and
    donation setting, and emit steady-state ms/step in the
    kernel-registry evidence format (ms + flops + the knobs), so the
    TPU-window gap hunt — attention impl x remat x donation — is one
    `tools/ablate_step.py plan3d...` command."""
    from paddle_tpu.models.facade import make_train_step
    from paddle_tpu.models.gpt import train_step
    from paddle_tpu.parallel.planner import plan_train
    n = len(jax.devices())
    cfg, params, opt, toks = build(cfg_kw, batch=batch, seq=seq)
    plan = plan_train(cfg, n, batch, overlap=overlap)
    mesh = plan.build_mesh()
    step = make_train_step(train_step, cfg=cfg, lr=1e-4, donate=donate,
                           mesh=mesh, plan=plan)
    t0 = time.perf_counter()
    loss, params, opt = step(params, opt, toks)
    float(loss)
    log(f"  compile+first {time.perf_counter() - t0:.1f}s "
        f"(plan {plan.name})")
    t0 = time.perf_counter()
    for _ in range(10):
        loss, params, opt = step(params, opt, toks)
    float(loss)
    ms = (time.perf_counter() - t0) / 10 * 1e3
    emit(row_name, ms, {
        "flops": _step_flops(cfg, params, batch, seq),
        "knobs": {"plan": plan.name, "donate": donate,
                  "remat": cfg.remat,
                  "remat_policy": cfg.remat_policy if cfg.remat
                  else "none", "n_devices": n,
                  "overlap": bool(getattr(plan, "overlap", False))},
        "traces": step.trace_count,
    })


def v_plan3d():
    os.environ["PADDLE_TPU_DISABLE_PALLAS_BWD"] = "1"
    _plan3d_variant("plan3d_dots_b8", dict(remat=True,
                                           remat_policy="dots"))


def v_plan3d_full():
    os.environ["PADDLE_TPU_DISABLE_PALLAS_BWD"] = "1"
    _plan3d_variant("plan3d_full_b8", dict(remat=True,
                                           remat_policy="full"))


def v_plan3d_noremat():
    os.environ["PADDLE_TPU_DISABLE_PALLAS_BWD"] = "1"
    _plan3d_variant("plan3d_noremat_b4", dict(remat=False), batch=4)


def v_plan3d_nodonate():
    """Donation OFF over the same plan as plan3d_dots: the delta prices
    what the pinned donation aliasing buys (two live copies of params +
    Adam moments, extra HBM traffic)."""
    os.environ["PADDLE_TPU_DISABLE_PALLAS_BWD"] = "1"
    _plan3d_variant("plan3d_dots_nodonate_b8",
                    dict(remat=True, remat_policy="dots"), donate=False)


def v_plan3d_overlap():
    """Overlap A/B (ISSUE 16): the plan3d_dots grid with the latency-
    hiding collective schedule on (plan.overlap -> the XLA async-
    collective/collective-matmul compiler options on TPU meshes; a
    no-op attachment on CPU, where the row pins parity + trace count).
    Run `plan3d plan3d_overlap` together — the delta IS the hidden
    coll_fsdp time."""
    os.environ["PADDLE_TPU_DISABLE_PALLAS_BWD"] = "1"
    _plan3d_variant("plan3d_overlap_b8",
                    dict(remat=True, remat_policy="dots"), overlap=True)


def v_fused_step():
    """Fused-kernel A/B (ISSUE 16): the plan3d_dots grid with BOTH
    fused Pallas step kernels forced on — one-pass CE+grad
    (kernels/pallas_ce.ce_fused_train) and the fused AdamW master
    update (kernels/pallas_update.fused_apply_adamw) — by pointing the
    registry resolution at them in-process (the shipped default stays
    off; tools/bench_fused_step.py --adopt is the only writer). On a
    non-TPU backend the kill-switch gates keep the oracles, so the row
    is only meaningful on the chip."""
    from paddle_tpu.kernels import registry as reg
    forced = {"ce": "pallas_fused", "fused_update": "pallas"}
    orig = reg.winner
    reg.winner = (lambda kernel, backend=None, bucket="*", path=None:
                  forced.get(kernel) or orig(kernel, backend=backend,
                                             bucket=bucket, path=path))
    try:
        _plan3d_variant("plan3d_fusedkernels_b8",
                        dict(remat=True, remat_policy="dots"))
    finally:
        reg.winner = orig


def v_train_attrib():
    """Achieved-vs-roofline evidence rows for the planned train step
    (ISSUE 12): run tools/train_attrib.py's measurement in-process for
    the plan this backend's device count admits and emit one
    kernel-registry-format row per plan — ms + step FLOPs + the ledger
    phase attribution + the HLO audit finding count — so the MFU gap
    hunt has per-phase attribution next to the plan3d timings."""
    import train_attrib as ta
    n = len(jax.devices())
    plans = "dp2_fsdp2_tp2,dp1_fsdp8_tp1" if n >= 8 else "dp1_fsdp1_tp1"
    args = type("A", (), {})()
    args.batch, args.seq, args.steps, args.every = 8, 1024, 10, 3
    args.hidden, args.layers, args.vocab = 1024, 24, 32768
    if jax.devices()[0].platform == "cpu":
        # ANY CPU run gets the test shape, not the flagship (a 24L
        # flagship step on a host core measures swap — at any device
        # count)
        args.hidden, args.layers, args.vocab = 128, 2, 512
        args.seq, args.steps = 32, 12
    args.jsonl_prefix = "/tmp/ablate_train_attrib"
    cfg = ta.build_cfg(args)
    for name in plans.split(","):
        row = ta.measure_plan(name, cfg, args, None, None, None)
        top = max(row["phases"].items(), key=lambda kv: kv[1]["share"])
        emit(f"train_attrib_{row['plan']}",
             row["measured_ms_per_step_p50"] or -1.0, {
                 "flops": row["model_flops_per_step"],
                 "roofline_ms": row["roofline_ms_per_step"],
                 "achieved_vs_roofline": row["achieved_vs_roofline"],
                 "peak_mfu": row["peak_mfu"],
                 "achieved_mfu": row["achieved_mfu"],
                 "bound_phase": f"{top[0]}({top[1]['bound']})",
                 "audit_findings": len(row["audit"]["findings"]),
                 "knobs": {"plan": row["plan"], "batch": args.batch,
                           "seq": args.seq,
                           "n_devices": len(jax.devices())},
             })


def v_sgd():
    """AdamW swapped for plain SGD: isolates optimizer-update cost."""
    from paddle_tpu.models import gpt as G
    cfg, p, o, t = build(dict(remat=True, remat_policy="full"))

    def sgd_step(params, opt_state, batch, cfg, lr=1e-4, **_kw):
        loss, grads = jax.value_and_grad(
            lambda pp: G.gpt_loss(pp, batch, cfg))(params)
        new_params = jax.tree_util.tree_map(
            lambda pp, g: (pp.astype(jnp.float32)
                           - lr * g.astype(jnp.float32)).astype(pp.dtype),
            params, grads)
        return loss, new_params, opt_state

    orig = G.train_step
    G.train_step = sgd_step
    try:
        emit("sgd_b8", step_ms(cfg, p, o, t))
    finally:
        G.train_step = orig


VARIANTS = {
    "calib": calib_matmul,
    "calib_attn": calib_attention,
    "baseline": v_baseline,
    "dots": v_dots,
    "dots_flash": v_dots_flash,
    "noremat_b4": v_noremat_b4,
    "xla_attn": v_xla_attn,
    "no_attn": v_no_attn,
    "fwd_only": v_fwd_only,
    "no_head": v_no_head,
    "sgd": v_sgd,
    "no_ln": v_no_ln,
    "no_mlp": v_no_mlp,
    "jaxflash": v_jaxflash,
    "splash": v_splash,
    # 3D sharded-step rows (ISSUE 10): remat x donation over the
    # planner-driven GSPMD step — run all four for the gap hunt
    "plan3d": v_plan3d,
    "plan3d_full": v_plan3d_full,
    "plan3d_noremat": v_plan3d_noremat,
    "plan3d_nodonate": v_plan3d_nodonate,
    # ISSUE 16 A/B rows: latency-hiding collectives and the fused step
    # kernels over the same plan3d_dots grid
    "plan3d_overlap": v_plan3d_overlap,
    "fused_step": v_fused_step,
    # per-phase roofline attribution + collective audit over the
    # planned step (ISSUE 12) — the evidence row every future MFU
    # optimization PR ships with
    "train_attrib": v_train_attrib,
}


def main():
    names = sys.argv[1:] or list(VARIANTS)
    devs = jax.devices()
    log(f"backend {devs[0].platform} ({devs[0].device_kind})")
    for n in names:
        log(f"=== {n} ===")
        # whole-environment snapshot: variants may set any kill-switch /
        # impl env freely and never leak it into the next variant, even
        # when they raise mid-flight
        snapshot = dict(os.environ)
        try:
            VARIANTS[n]()
        except Exception as e:
            emit(n, -1.0, {"error": repr(e)[:200]})
            log(f"variant {n} failed: {e!r}")
        finally:
            os.environ.clear()
            os.environ.update(snapshot)


if __name__ == "__main__":
    main()
