"""Summarize a run's telemetry JSONL (profiler/telemetry.py stream).

Turns the batched step-metrics stream into the post-run numbers an
operator (or bench.py / tools/chaos_drill.py) wants: step-time
percentiles EXCLUDING the compile window, items/sec, per-field loss/
norm statistics, monitor-counter deltas, and the event timeline.

Step timing comes from the `flush` boundary records (the pipeline's
whole point is that individual steps never touch the host clock): each
flush stamps wall time and the number of steps it covers, so
ms/step = (t_flush[i] - t_flush[i-1]) / n[i]. The first flush window
absorbs the jit compile and is excluded from the percentiles (it is
reported separately as compile_window_ms_per_step).

Usage:
  python tools/telemetry_report.py RUN.jsonl          # one JSON line
  python tools/telemetry_report.py RUN.jsonl --pretty
"""
from __future__ import annotations

import argparse
import json
import math
import sys
from typing import Optional


def _percentile(ordered, q: float):
    """Nearest-rank percentile of an ascending list."""
    if not ordered:
        return None
    n = len(ordered)
    return ordered[max(0, math.ceil(q / 100.0 * n) - 1)]


def _field_stats(values):
    vals = [v for v in values if v is not None and not math.isnan(v)]
    if not vals:
        return None
    ordered = sorted(vals)
    return {"n": len(vals), "first": vals[0], "last": vals[-1],
            "min": ordered[0], "max": ordered[-1],
            "mean": sum(vals) / len(vals)}


def summarize(path: str, samples_per_step: Optional[float] = None) -> dict:
    """Parse a telemetry JSONL file into one summary dict."""
    run = {}
    runs = []          # every header, in order (restarts append new ones)
    steps = []
    flushes = []
    flush_groups = []  # flushes bucketed per run header, in file order —
    #                    windows must not span a kill/restart boundary
    monitors = []
    events = []
    slo_ttft, slo_itl = [], []   # serving SLO samples (serving_slo recs)
    bad_lines = 0
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                bad_lines += 1       # torn tail of a killed writer
                continue
            kind = rec.get("kind")
            if kind == "run":
                run = rec
                runs.append(rec)
                flush_groups.append([])
            elif kind == "step":
                steps.append(rec)
            elif kind == "flush":
                flushes.append(rec)
                if not flush_groups:
                    flush_groups.append([])
                flush_groups[-1].append(rec)
            elif kind == "monitor":
                monitors.append(rec)
            elif kind == "event":
                events.append(rec)
            elif kind == "serving_slo":
                slo_ttft.extend(rec.get("ttft_ms") or [])
                slo_itl.extend(rec.get("itl_ms") or [])

    out = {"path": path, "run": {k: v for k, v in run.items()
                                 if k not in ("kind",)},
           "runs": len(runs),
           "steps_recorded": len(steps), "flushes": len(flushes),
           "bad_lines": bad_lines}

    # ---- step time from flush deltas, per run group (each process's
    # first window absorbs ITS jit compile; pairing flushes across a
    # restart boundary would count the kill-to-restart gap + recompile
    # as a step-time tail) ----
    win_ms = []            # (ms_per_step, steps_in_window)
    for group in flush_groups:
        for prev, cur in zip(group, group[1:]):
            n = cur.get("n") or 0
            dt = cur["t"] - prev["t"]
            if n > 0 and dt >= 0:
                win_ms.append((dt * 1e3 / n, n))
    if flushes and steps and runs:
        # FIRST header vs its first flush (a later header belongs to a
        # restarted process)
        first_n = flushes[0].get("n") or 0
        dt0 = flushes[0]["t"] - runs[0].get("t", flushes[0]["t"])
        if first_n and dt0 >= 0:
            out["compile_window_ms_per_step"] = round(dt0 * 1e3 / first_n,
                                                      3)
    if win_ms:
        per_step = sorted(m for m, _ in win_ms)
        total_steps = sum(n for _, n in win_ms)
        total_s = sum(m * n for m, n in win_ms) / 1e3
        st = {
            "windows": len(win_ms),
            "steps": total_steps,
            "mean_ms": round(total_s * 1e3 / total_steps, 3),
            "p50_ms": round(_percentile(per_step, 50), 3),
            "p95_ms": round(_percentile(per_step, 95), 3),
            "max_ms": round(per_step[-1], 3),
        }
        sps = samples_per_step if samples_per_step is not None \
            else run.get("samples_per_step")
        if sps and total_s > 0:
            st["ips"] = round(total_steps * float(sps) / total_s, 1)
        out["step_time"] = st

    # ---- per-field scalar stats ----
    fields = run.get("fields") or sorted(
        {k for r in steps for k in r} - {"kind", "step"})
    fstats = {}
    for f in fields:
        s = _field_stats([r.get(f) for r in steps])
        if s is not None:
            fstats[f] = {k: (round(v, 6) if isinstance(v, float) else v)
                         for k, v in s.items()}
    if fstats:
        out["fields"] = fstats
    nonfinite = [r for r in steps
                 if (r.get("nonfinite") or 0) > 0
                 or (r.get("ok") is not None and r.get("ok") == 0.0)]
    out["bad_steps"] = [r["step"] for r in nonfinite][:32]

    # ---- monitor counter deltas (first vs last snapshot) ----
    if monitors:
        first, last = monitors[0]["stats"], monitors[-1]["stats"]
        out["monitor"] = last
        out["monitor_delta"] = {
            k: round(last[k] - first.get(k, 0), 6)
            for k in sorted(last) if last[k] != first.get(k, 0)}

    # ---- 3D training plan (parallel/planner.plan_train publishes the
    # chosen degrees as the train.plan.* gauge family; the async-
    # checkpoint counters ride the same snapshots). Counters report
    # first-to-last deltas, gauges their last value. ----
    if monitors:
        first_s, last_s = monitors[0]["stats"], monitors[-1]["stats"]
        tplan = {k[len("train.plan."):]: last_s[k]
                 for k in sorted(last_s) if k.startswith("train.plan.")}
        if tplan:
            ck = {}
            if "checkpoint_async_save" in last_s:
                ck["async_saves"] = (last_s["checkpoint_async_save"]
                                     - first_s.get("checkpoint_async_save",
                                                   0))
            if "checkpoint_async_pending" in last_s:
                ck["async_pending"] = last_s["checkpoint_async_pending"]
            if "checkpoint_save_ms" in last_s:
                ck["last_save_ms"] = last_s["checkpoint_save_ms"]
            if ck:
                tplan["checkpoint"] = ck
            out["train_plan"] = tplan

    # ---- serving-engine stats (inference/serving.py monitor names:
    # slot occupancy/queue depth gauges, token/prefill/tick counters;
    # tools/bench_serving.py snapshots the registry into this stream).
    # Counters report first-to-last DELTAS (consistent with the
    # monitor_delta section and with tokens_per_s); gauges report their
    # last value. ----
    _SERVING_GAUGES = ("serving.slot_occupancy", "serving.queue_depth",
                       "serving.queue_wait_ms", "serving.pages_in_use",
                       "serving.pages_shared", "serving.spec_accept_rate",
                       "serving.quant_weights_bytes",
                       "serving.fp_weights_bytes",
                       "serving.router.replicas_live",
                       "serving.router.pending")

    def _is_gauge(k):
        # per-replica queue-depth gauges carry a dynamic suffix
        # (serving.router.queue_depth.r<i>, inference/router.py)
        return (k in _SERVING_GAUGES
                or k.startswith("serving.router.queue_depth."))
    # the paged-KV pool surface (inference/serving.py "kv pool"):
    # occupancy/sharing gauges + COW and chunked-prefill counters,
    # grouped under serving.kv_pool when any of them moved
    _KV_POOL = ("pages_in_use", "pages_shared", "cow_copies",
                "prefill_chunks")
    # the speculative-decode surface (inference/spec_decode.py):
    # proposed/accepted counter deltas + the per-engine acceptance-rate
    # gauge, grouped under serving.spec when any of them moved
    _SPEC = ("spec_proposed", "spec_accepted", "spec_accept_rate")
    # the weight-only quant surface (inference/serving.py quant=):
    # fp-vs-int8 weight-bytes gauges + the fused dequant-matmul
    # counter, grouped under serving.quant when any of them moved
    _QUANT = ("quant_weights_bytes", "fp_weights_bytes",
              "quant_matmuls")
    if monitors:
        first_s, last_s = monitors[0]["stats"], monitors[-1]["stats"]
        srv = {k[len("serving."):]:
               (last_s[k] if _is_gauge(k)
                else last_s[k] - first_s.get(k, 0))
               for k in sorted(last_s) if k.startswith("serving.")}
        if srv:
            dtok = srv.get("tokens_emitted", 0)
            dt = monitors[-1]["t"] - monitors[0]["t"]
            if dtok and dt > 0:
                srv["tokens_per_s"] = round(dtok / dt, 1)
            pool = {k: srv.pop(k) for k in _KV_POOL if k in srv}
            if any(pool.values()):
                srv["kv_pool"] = pool
            spec = {k: srv.pop(k) for k in _SPEC if k in srv}
            if any(spec.values()):
                srv["spec"] = spec
            quant = {k: srv.pop(k) for k in _QUANT if k in srv}
            if any(quant.values()):
                if quant.get("quant_weights_bytes") and \
                        quant.get("fp_weights_bytes"):
                    quant["weight_bytes_ratio"] = round(
                        quant["quant_weights_bytes"]
                        / quant["fp_weights_bytes"], 3)
                srv["quant"] = quant
            # the replicated-engine router surface (inference/router.py
            # serving.router.*): liveness/requeue/balance, grouped —
            # per-replica queue depths and dispatch counters keep their
            # r<i> suffixes inside the block
            router = {k[len("router."):]: srv.pop(k)
                      for k in [k for k in srv
                                if k.startswith("router.")]}
            if any(router.values()):
                srv["router"] = router
            out["serving"] = srv

    # ---- serving SLO percentiles (ServingEngine.export_slo_jsonl
    # records: raw TTFT / inter-token-latency samples in ms) ----
    def _slo_pcts(vals):
        ordered = sorted(vals)
        return {"n": len(vals),
                "p50_ms": round(_percentile(ordered, 50), 3),
                "p95_ms": round(_percentile(ordered, 95), 3),
                "p99_ms": round(_percentile(ordered, 99), 3)}
    if slo_ttft or slo_itl:
        srv = out.setdefault("serving", {})
        if slo_ttft:
            srv["ttft"] = _slo_pcts(slo_ttft)
        if slo_itl:
            srv["inter_token"] = _slo_pcts(slo_itl)

    # ---- event timeline ----
    if events:
        t0 = events[0]["t"]
        out["events"] = [
            {"name": e.get("name"), "at_s": round(e["t"] - t0, 3),
             "dur_s": round(e.get("dur_s") or 0.0, 6)}
            for e in sorted(events, key=lambda e: e["t"])[:64]]
    return out


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("jsonl", help="telemetry JSONL file")
    ap.add_argument("--pretty", action="store_true")
    ap.add_argument("--samples-per-step", type=float, default=None,
                    help="items per step for ips (overrides the run "
                         "header)")
    args = ap.parse_args()
    try:
        doc = summarize(args.jsonl, samples_per_step=args.samples_per_step)
    except OSError as e:
        print(f"cannot read {args.jsonl}: {e}", file=sys.stderr)
        return 2
    print(json.dumps(doc, indent=1 if args.pretty else None))
    return 0


if __name__ == "__main__":
    sys.exit(main())
