"""Summarize a run's telemetry JSONL (profiler/telemetry.py stream).

Turns the batched step-metrics stream into the post-run numbers an
operator (or bench.py / tools/chaos_drill.py) wants: step-time
percentiles EXCLUDING the compile window, items/sec, per-field loss/
norm statistics, monitor-counter deltas, and the event timeline.

Step timing comes from the `flush` boundary records (the pipeline's
whole point is that individual steps never touch the host clock): each
flush stamps wall time and the number of steps it covers, so
ms/step = (t_flush[i] - t_flush[i-1]) / n[i]. The first flush window
absorbs the jit compile and is excluded from the percentiles (it is
reported separately as compile_window_ms_per_step).

Fleet mode (`--fleet`): merge multiple per-replica serving JSONLs
(a router's engines each stream their own `<path>.r<i>` —
inference/router.create_router) into ONE aggregate report: per-replica
balance (ticks/tokens/throughput per file), fleet-wide TTFT /
inter-token percentiles over the union of samples, and an SLO
burn-rate summary (profiler/slo) against the --ttft-slo-ms /
--itl-slo-ms objectives. tools/bench_serving.py --router drives it.

Usage:
  python tools/telemetry_report.py RUN.jsonl          # one JSON line
  python tools/telemetry_report.py RUN.jsonl --pretty
  python tools/telemetry_report.py --fleet R.jsonl.r0 R.jsonl.r1 ...
"""
from __future__ import annotations

import argparse
import json
import math
import sys
from typing import Optional


def _percentile(ordered, q: float):
    """Nearest-rank percentile of an ascending list."""
    if not ordered:
        return None
    n = len(ordered)
    return ordered[max(0, math.ceil(q / 100.0 * n) - 1)]


def _field_stats(values):
    vals = [v for v in values if v is not None and not math.isnan(v)]
    if not vals:
        return None
    ordered = sorted(vals)
    return {"n": len(vals), "first": vals[0], "last": vals[-1],
            "min": ordered[0], "max": ordered[-1],
            "mean": sum(vals) / len(vals)}


def summarize(path: str, samples_per_step: Optional[float] = None) -> dict:
    """Parse a telemetry JSONL file into one summary dict."""
    run = {}
    runs = []          # every header, in order (restarts append new ones)
    steps = []
    train_attribs = [] # achieved-vs-roofline joins (tools/train_attrib)
    flushes = []
    flush_groups = []  # flushes bucketed per run header, in file order —
    #                    windows must not span a kill/restart boundary
    monitors = []
    events = []
    slo_ttft, slo_itl = [], []   # serving SLO samples (serving_slo recs)
    srv_run = {}                 # serving_run header (engine layout)
    srv_ticks = []               # in-tick serving telemetry records
    srv_prefills = []
    bad_lines = 0
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                bad_lines += 1       # torn tail of a killed writer
                continue
            kind = rec.get("kind")
            if kind == "run":
                run = rec
                runs.append(rec)
                flush_groups.append([])
            elif kind == "step":
                steps.append(rec)
            elif kind == "flush":
                flushes.append(rec)
                if not flush_groups:
                    flush_groups.append([])
                flush_groups[-1].append(rec)
            elif kind == "monitor":
                monitors.append(rec)
            elif kind == "event":
                events.append(rec)
            elif kind == "train_attrib":
                train_attribs.append(rec)
            elif kind == "serving_slo":
                slo_ttft.extend(rec.get("ttft_ms") or [])
                slo_itl.extend(rec.get("itl_ms") or [])
            elif kind == "serving_run":
                srv_run = rec
            elif kind == "serving_tick":
                srv_ticks.append(rec)
            elif kind == "serving_prefill":
                srv_prefills.append(rec)

    out = {"path": path, "run": {k: v for k, v in run.items()
                                 if k not in ("kind",)},
           "runs": len(runs),
           "steps_recorded": len(steps), "flushes": len(flushes),
           "bad_lines": bad_lines}

    # ---- step time from flush deltas, per run group (each process's
    # first window absorbs ITS jit compile; pairing flushes across a
    # restart boundary would count the kill-to-restart gap + recompile
    # as a step-time tail) ----
    win_ms = []            # (ms_per_step, steps_in_window)
    for group in flush_groups:
        for prev, cur in zip(group, group[1:]):
            n = cur.get("n") or 0
            dt = cur["t"] - prev["t"]
            if n > 0 and dt >= 0:
                win_ms.append((dt * 1e3 / n, n))
    if flushes and steps and runs:
        # FIRST header vs its first flush (a later header belongs to a
        # restarted process)
        first_n = flushes[0].get("n") or 0
        dt0 = flushes[0]["t"] - runs[0].get("t", flushes[0]["t"])
        if first_n and dt0 >= 0:
            out["compile_window_ms_per_step"] = round(dt0 * 1e3 / first_n,
                                                      3)
    if win_ms:
        per_step = sorted(m for m, _ in win_ms)
        total_steps = sum(n for _, n in win_ms)
        total_s = sum(m * n for m, n in win_ms) / 1e3
        st = {
            "windows": len(win_ms),
            "steps": total_steps,
            "mean_ms": round(total_s * 1e3 / total_steps, 3),
            "p50_ms": round(_percentile(per_step, 50), 3),
            "p95_ms": round(_percentile(per_step, 95), 3),
            "max_ms": round(per_step[-1], 3),
        }
        sps = samples_per_step if samples_per_step is not None \
            else run.get("samples_per_step")
        if sps and total_s > 0:
            st["ips"] = round(total_steps * float(sps) / total_s, 1)
        out["step_time"] = st

    # ---- per-field scalar stats ----
    fields = run.get("fields") or sorted(
        {k for r in steps for k in r} - {"kind", "step"})
    fstats = {}
    for f in fields:
        s = _field_stats([r.get(f) for r in steps])
        if s is not None:
            fstats[f] = {k: (round(v, 6) if isinstance(v, float) else v)
                         for k, v in s.items()}
    if fstats:
        out["fields"] = fstats
    nonfinite = [r for r in steps
                 if (r.get("nonfinite") or 0) > 0
                 or (r.get("ok") is not None and r.get("ok") == 0.0)]
    out["bad_steps"] = [r["step"] for r in nonfinite][:32]

    # ---- monitor counter deltas (first vs last snapshot).
    # Histogram stats render as dicts (profiler/monitor.Histogram
    # snapshots {"n","p50","p95","p99",...}) — they report their LAST
    # snapshot, not a delta ----
    if monitors:
        first, last = monitors[0]["stats"], monitors[-1]["stats"]
        out["monitor"] = last
        out["monitor_delta"] = {
            k: (last[k] if isinstance(last[k], dict)
                or isinstance(first.get(k, 0), dict)
                else round(last[k] - first.get(k, 0), 6))
            for k in sorted(last) if last[k] != first.get(k, 0)}

    # ---- 3D training plan (parallel/planner.plan_train publishes the
    # chosen degrees as the train.plan.* gauge family; the async-
    # checkpoint counters ride the same snapshots). Counters report
    # first-to-last deltas, gauges their last value. ----
    if monitors:
        first_s, last_s = monitors[0]["stats"], monitors[-1]["stats"]
        tplan = {k[len("train.plan."):]: last_s[k]
                 for k in sorted(last_s) if k.startswith("train.plan.")}
        if tplan and "train.bubble_fraction" in last_s:
            # the pp step's measured 1F1B schedule bubble (gauge: last
            # value) rides the plan block — the pair (pp, bubble) is
            # the 4D plan's efficiency signature
            tplan["bubble_fraction"] = last_s["train.bubble_fraction"]
        if tplan:
            ck = {}
            if "checkpoint_async_save" in last_s:
                ck["async_saves"] = (last_s["checkpoint_async_save"]
                                     - first_s.get("checkpoint_async_save",
                                                   0))
            if "checkpoint_async_pending" in last_s:
                ck["async_pending"] = last_s["checkpoint_async_pending"]
            if "checkpoint_save_ms" in last_s:
                ck["last_save_ms"] = last_s["checkpoint_save_ms"]
            if ck:
                tplan["checkpoint"] = ck
            out["train_plan"] = tplan

    # ---- elastic replans (parallel/elastic.py train.elastic.* family:
    # replans/device_loss/collective_hang counters report first-to-last
    # deltas; world_size/replan_ms/reshard_bytes gauges their last
    # value — "replan is priced and observable", ISSUE 14) ----
    if monitors:
        first_s, last_s = monitors[0]["stats"], monitors[-1]["stats"]
        _ELASTIC_GAUGES = ("world_size", "replan_ms", "reshard_bytes")
        ela = {}
        for k in sorted(last_s):
            if not k.startswith("train.elastic."):
                continue
            name = k[len("train.elastic."):]
            ela[name] = (last_s[k] if name in _ELASTIC_GAUGES
                         else last_s[k] - first_s.get(k, 0))
        if ela:
            out["elastic"] = ela

    # ---- achieved MFU + compile observability (the train.mfu /
    # train.tokens_per_s gauges the telemetry flush publishes when
    # wired with flops_per_token=, and the train.compile.* stats from
    # models/facade + profiler/hlo_audit). Gauges report last value. ----
    if monitors:
        last_s = monitors[-1]["stats"]
        mfu = {}
        if "train.mfu" in last_s:
            mfu["mfu"] = last_s["train.mfu"]
        if "train.tokens_per_s" in last_s:
            mfu["tokens_per_s"] = last_s["train.tokens_per_s"]
        comp = {k[len("train.compile."):]: last_s[k]
                for k in sorted(last_s)
                if k.startswith("train.compile.")}
        if comp:
            mfu["compile"] = comp
        if mfu:
            out["mfu"] = mfu

    # ---- memory observability (profiler/mem_audit.py): the hbm.*
    # live gauges ride every telemetry flush (PJRT memory_stats, or
    # host RSS on CPU), serving.kv_pool_bytes sits next to the pool
    # occupancy gauges, the oom_forensics counters count flight dumps,
    # and the {train,serving}.mem.* family carries the last compiled-
    # memory audit. Gauges report last value; counters deltas. ----
    if monitors:
        first_s, last_s = monitors[0]["stats"], monitors[-1]["stats"]
        mem = {}
        hbm = {k[len("hbm."):]: last_s[k]
               for k in sorted(last_s) if k.startswith("hbm.")}
        if hbm:
            mem["hbm"] = hbm
        if "serving.kv_pool_bytes" in last_s:
            mem["kv_pool_bytes"] = last_s["serving.kv_pool_bytes"]
        if "serving.kv_host_bytes" in last_s:
            mem["kv_host_bytes"] = last_s["serving.kv_host_bytes"]
        oom = {}
        for k in ("train.oom_forensics", "serving.oom_forensics"):
            if k in last_s:
                oom[k.split(".")[0]] = last_s[k] - first_s.get(k, 0)
        if oom:
            mem["oom_forensics"] = oom
        audit = {}
        for fam in ("train", "serving"):
            pre = fam + ".mem."
            fam_stats = {k[len(pre):]: last_s[k]
                         for k in sorted(last_s) if k.startswith(pre)}
            if fam_stats:
                if "audits" in fam_stats:     # the only counter here
                    fam_stats["audits"] -= first_s.get(pre + "audits",
                                                       0)
                audit[fam] = fam_stats
        if audit:
            mem["audit"] = audit
        if mem:
            out["memory"] = mem

    # ---- achieved-vs-roofline joins embedded in the stream
    # (tools/train_attrib.py appends one per measured plan) ----
    if train_attribs:
        out["train_attrib"] = [
            {k: v for k, v in r.items() if k != "kind"}
            for r in train_attribs]

    # ---- serving-engine stats (inference/serving.py monitor names:
    # slot occupancy/queue depth gauges, token/prefill/tick counters;
    # tools/bench_serving.py snapshots the registry into this stream).
    # Counters report first-to-last DELTAS (consistent with the
    # monitor_delta section and with tokens_per_s); gauges report their
    # last value. ----
    _SERVING_GAUGES = ("serving.slot_occupancy", "serving.queue_depth",
                       "serving.queue_wait_ms", "serving.pages_in_use",
                       "serving.pages_shared", "serving.spec_accept_rate",
                       "serving.quant_weights_bytes",
                       "serving.fp_weights_bytes",
                       "serving.router.replicas_live",
                       "serving.router.pending",
                       "serving.router.suspended",
                       "serving.brownout_level",
                       "serving.autoscale.replicas_target",
                       "serving.autoscale.occupancy",
                       "serving.autoscale.migrated_pages_bytes",
                       "serving.kv_pool_bytes",
                       "serving.kv_host_bytes",
                       "serving.ticks_per_pull")

    def _is_gauge(k):
        # per-replica queue-depth gauges carry a dynamic suffix
        # (serving.router.queue_depth.r<i>, inference/router.py)
        return (k in _SERVING_GAUGES
                or k.startswith("serving.router.queue_depth."))
    # the paged-KV pool surface (inference/serving.py "kv pool"):
    # occupancy/sharing gauges + COW and chunked-prefill counters,
    # grouped under serving.kv_pool when any of them moved
    _KV_POOL = ("pages_in_use", "pages_shared", "cow_copies",
                "prefill_chunks", "kv_pool_bytes")
    # the speculative-decode surface (inference/spec_decode.py):
    # proposed/accepted counter deltas + the per-engine acceptance-rate
    # gauge, grouped under serving.spec when any of them moved
    _SPEC = ("spec_proposed", "spec_accepted", "spec_accept_rate")
    # the weight-only quant surface (inference/serving.py quant=):
    # fp-vs-int8 weight-bytes gauges + the fused dequant-matmul
    # counter, grouped under serving.quant when any of them moved
    _QUANT = ("quant_weights_bytes", "fp_weights_bytes",
              "quant_matmuls")
    # the disaggregation surface (inference/multi_tick.py +
    # inference/host_kv.py): the multi-tick K gauge, the host-tier
    # occupancy gauge, and the spill/swap-in counters, grouped under
    # serving.disagg when any of them moved (router handoffs stay in
    # the router block — they are a fleet stat, not an engine stat)
    _DISAGG = ("ticks_per_pull", "kv_host_bytes", "host_spills",
               "host_swapins")
    def _stat_val(k, last_s, first_s):
        # gauges and histograms (dict snapshots) report last value;
        # counters report the first-to-last delta
        v = last_s[k]
        if _is_gauge(k) or isinstance(v, dict) \
                or isinstance(first_s.get(k, 0), dict):
            return v
        return v - first_s.get(k, 0)
    if monitors:
        first_s, last_s = monitors[0]["stats"], monitors[-1]["stats"]
        srv = {k[len("serving."):]: _stat_val(k, last_s, first_s)
               for k in sorted(last_s) if k.startswith("serving.")}
        if srv:
            dtok = srv.get("tokens_emitted", 0)
            dt = monitors[-1]["t"] - monitors[0]["t"]
            if dtok and dt > 0:
                srv["tokens_per_s"] = round(dtok / dt, 1)
            pool = {k: srv.pop(k) for k in _KV_POOL if k in srv}
            if any(pool.values()):
                srv["kv_pool"] = pool
            spec = {k: srv.pop(k) for k in _SPEC if k in srv}
            if any(spec.values()):
                srv["spec"] = spec
            disagg = {k: srv.pop(k) for k in _DISAGG if k in srv}
            if any(disagg.values()):
                # tokens per dispatch: the multi-tick economics in one
                # number (== K on a saturated single stream, lower when
                # early-exit masks trim the scan)
                dtok = srv.get("tokens_emitted", 0)
                dticks = srv.get("decode_ticks", 0)
                if dtok and dticks:
                    disagg["tokens_per_dispatch"] = round(
                        dtok / dticks, 2)
                srv["disagg"] = disagg
            quant = {k: srv.pop(k) for k in _QUANT if k in srv}
            if any(quant.values()):
                if quant.get("quant_weights_bytes") and \
                        quant.get("fp_weights_bytes"):
                    quant["weight_bytes_ratio"] = round(
                        quant["quant_weights_bytes"]
                        / quant["fp_weights_bytes"], 3)
                srv["quant"] = quant
            # the replicated-engine router surface (inference/router.py
            # serving.router.*): liveness/requeue/balance, grouped —
            # per-replica queue depths and dispatch counters keep their
            # r<i> suffixes inside the block
            router = {k[len("router."):]: srv.pop(k)
                      for k in [k for k in srv
                                if k.startswith("router.")]}
            if any(router.values()):
                srv["router"] = router
            # the serving control loop (inference/autoscale.py +
            # router migration counters, serving.autoscale.*):
            # scale_out/scale_in/migrations/preemptions deltas, the
            # replicas_target/occupancy/migrated_pages_bytes gauges
            auto = {k[len("autoscale."):]: srv.pop(k)
                    for k in [k for k in srv
                              if k.startswith("autoscale.")]}
            if any(auto.values()):
                srv["autoscale"] = auto
            # the overload-resilience surface (inference/admission.py
            # + brownout.py + journal.py): per-tenant admitted/
            # rejected/suspended counter deltas (dynamic .<tenant>
            # suffixes kept inside the block), preemption/resume
            # counters, the brownout level gauge + transition/shed
            # counters, and the request-journal WAL counters — ONE
            # "admission" block, the overload story in one place
            adm = {k[len("admission."):]: srv.pop(k)
                   for k in [k for k in srv
                             if k.startswith("admission.")]}
            for k in [k for k in srv if k.startswith("brownout.")
                      or k.startswith("journal.")]:
                adm[k] = srv.pop(k)
            if "brownout_level" in srv:
                adm["brownout_level"] = srv.pop("brownout_level")
            if any(adm.values()):
                srv["admission"] = adm
            # the compiled-memory audit family reports (correctly
            # typed) under out["memory"]["audit"]["serving"] instead
            for k in [k for k in srv if k.startswith("mem.")]:
                srv.pop(k)
            out["serving"] = srv

    # ---- serving SLO percentiles (ServingEngine.export_slo_jsonl
    # records: raw TTFT / inter-token-latency samples in ms) ----
    def _slo_pcts(vals):
        ordered = sorted(vals)
        return {"n": len(vals),
                "p50_ms": round(_percentile(ordered, 50), 3),
                "p95_ms": round(_percentile(ordered, 95), 3),
                "p99_ms": round(_percentile(ordered, 99), 3)}
    if slo_ttft or slo_itl:
        srv = out.setdefault("serving", {})
        if slo_ttft:
            srv["ttft"] = _slo_pcts(slo_ttft)
        if slo_itl:
            srv["inter_token"] = _slo_pcts(slo_itl)

    # ---- in-tick serving telemetry (profiler/serving_telemetry
    # serving_tick / serving_prefill records: the per-tick device
    # fields riding the token pull + tick wall ms) ----
    if srv_ticks:
        dur = sorted(r.get("dur_ms", 0.0) for r in srv_ticks)
        blk = {
            "ticks": len(srv_ticks),
            "tokens": sum(r.get("tokens") or 0 for r in srv_ticks),
            "dur_ms_p50": round(_percentile(dur, 50), 3),
            "dur_ms_p95": round(_percentile(dur, 95), 3),
            "mean_active": round(sum(r.get("active") or 0
                                     for r in srv_ticks)
                                 / len(srv_ticks), 2),
            "poisoned": sum(r.get("poisoned") or 0 for r in srv_ticks),
        }
        att = [r["attended"] for r in srv_ticks if "attended" in r]
        if att:
            blk["mean_attended"] = round(sum(att) / len(att), 1)
        prop = sum(r.get("spec_proposed") or 0 for r in srv_ticks)
        if prop:
            acc = sum(r.get("spec_accepted") or 0 for r in srv_ticks)
            blk["spec_accept_rate"] = round(acc / prop, 3)
        if srv_prefills:
            pdur = sorted(r.get("dur_ms", 0.0) for r in srv_prefills)
            blk["prefills"] = len(srv_prefills)
            blk["prefill_ms_p50"] = round(_percentile(pdur, 50), 3)
        if srv_run:
            blk["engine"] = {k: srv_run[k] for k in
                             ("family", "layout", "spec", "quant", "tp")
                             if k in srv_run}
        out["serving_ticks"] = blk

    # ---- event timeline ----
    if events:
        t0 = events[0]["t"]
        out["events"] = [
            {"name": e.get("name"), "at_s": round(e["t"] - t0, 3),
             "dur_s": round(e.get("dur_s") or 0.0, 6)}
            for e in sorted(events, key=lambda e: e["t"])[:64]]
    return out


def summarize_fleet(paths, ttft_slo_ms: float = 1000.0,
                    itl_slo_ms: float = 200.0,
                    error_budget: float = 0.01) -> dict:
    """Merge per-replica serving JSONLs (router + N engines) into one
    fleet report: per-replica balance, fleet-wide SLO percentiles over
    the UNION of samples, and the burn-rate summary against the given
    objectives (profiler/slo — the whole-file span is treated as one
    window, so the summary answers "did this run burn its budget",
    not "when")."""
    import os
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if repo not in sys.path:            # script-mode: tools/ is path[0]
        sys.path.insert(0, repo)
    from paddle_tpu.profiler.slo import BurnRateMonitor, Objective

    per_replica = []
    all_ttft, all_itl = [], []
    tick_ts = []
    total_tokens = 0
    for path in paths:
        doc = summarize(path)
        blk = doc.get("serving_ticks") or {}
        ttft = (doc.get("serving") or {}).get("ttft") or {}
        row = {"path": path,
               "ticks": blk.get("ticks", 0),
               "tokens": blk.get("tokens", 0),
               "dur_ms_p50": blk.get("dur_ms_p50"),
               "mean_active": blk.get("mean_active"),
               "ttft_n": ttft.get("n", 0)}
        per_replica.append(row)
        total_tokens += row["tokens"]
        # re-read the raw SLO samples (summarize only keeps pcts)
        with open(path) as f:
            for line in f:
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if rec.get("kind") == "serving_slo":
                    all_ttft.extend(rec.get("ttft_ms") or [])
                    all_itl.extend(rec.get("itl_ms") or [])
                elif rec.get("kind") == "serving_tick":
                    tick_ts.append(rec.get("t", 0.0))

    def _pcts(vals):
        if not vals:
            return None
        ordered = sorted(vals)
        return {"n": len(vals),
                "p50_ms": round(_percentile(ordered, 50), 3),
                "p95_ms": round(_percentile(ordered, 95), 3),
                "p99_ms": round(_percentile(ordered, 99), 3)}

    out = {"replicas": len(paths),
           "per_replica": per_replica,
           "tokens_total": total_tokens}
    if per_replica and total_tokens:
        toks = [r["tokens"] for r in per_replica]
        out["balance"] = {"tokens": toks,
                          "imbalance": round(
                              (max(toks) - min(toks))
                              / max(max(toks), 1), 3)}
    fleet = {}
    if all_ttft:
        fleet["ttft"] = _pcts(all_ttft)
    if all_itl:
        fleet["inter_token"] = _pcts(all_itl)
    if fleet:
        out["fleet"] = fleet

    # burn-rate summary: one window spanning the run
    span = (max(tick_ts) - min(tick_ts) + 1.0) if tick_ts else 60.0
    now = max(tick_ts) if tick_ts else None
    mon = BurnRateMonitor(
        [Objective("ttft_p99", "ttft", "latency",
                   threshold_ms=ttft_slo_ms, budget=error_budget),
         Objective("itl_p99", "itl", "latency",
                   threshold_ms=itl_slo_ms, budget=error_budget)],
        pairs=((span + 1.0, span / 2 + 0.5),))
    t_mid = now if now is not None else None
    if all_ttft:
        mon.observe_latency("ttft", all_ttft, t=t_mid)
    if all_itl:
        mon.observe_latency("itl", all_itl, t=t_mid)
    alerts = mon.check(now=now, flight=False)
    out["burn_rate"] = {
        "objectives": {"ttft_slo_ms": ttft_slo_ms,
                       "itl_slo_ms": itl_slo_ms,
                       "error_budget": error_budget},
        "window_s": round(span, 1),
        "burn_rates": mon.burn_rates(now),
        "alerts": [a.to_dict() for a in alerts]}
    return out


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("jsonl", nargs="*", help="telemetry JSONL file(s)")
    ap.add_argument("--pretty", action="store_true")
    ap.add_argument("--samples-per-step", type=float, default=None,
                    help="items per step for ips (overrides the run "
                         "header)")
    ap.add_argument("--fleet", action="store_true",
                    help="merge the given per-replica serving JSONLs "
                         "into one aggregate fleet report")
    ap.add_argument("--ttft-slo-ms", type=float, default=1000.0,
                    help="--fleet: TTFT latency objective")
    ap.add_argument("--itl-slo-ms", type=float, default=200.0,
                    help="--fleet: inter-token latency objective")
    ap.add_argument("--error-budget", type=float, default=0.01,
                    help="--fleet: allowed bad-sample fraction")
    args = ap.parse_args()
    if not args.jsonl:
        ap.error("need at least one JSONL path")
    try:
        if args.fleet:
            doc = summarize_fleet(args.jsonl,
                                  ttft_slo_ms=args.ttft_slo_ms,
                                  itl_slo_ms=args.itl_slo_ms,
                                  error_budget=args.error_budget)
        else:
            if len(args.jsonl) != 1:
                ap.error("multiple JSONLs need --fleet")
            doc = summarize(args.jsonl[0],
                            samples_per_step=args.samples_per_step)
    except OSError as e:
        print(f"cannot read {args.jsonl}: {e}", file=sys.stderr)
        return 2
    print(json.dumps(doc, indent=1 if args.pretty else None))
    return 0


if __name__ == "__main__":
    sys.exit(main())
