"""int8 vs bf16 matmul microbench row (round-3 verdict item 3: show the
real-int8 path's on-chip rate next to the bf16 MXU rate).

Times three variants of the serving matmul shape [B*S, D] @ [D, 4D]
chained through a lax.scan (one dispatch, the tunnel-latency rule from
CLAUDE.md):
  - bf16 @ bf16 -> f32 accumulate (the fp serving path)
  - int8 @ int8 -> i32 accumulate (raw MXU int8 rate)
  - the full Int8Linear op (quantize epilogue + int8 dot + dequant)
Emits one JSON line per variant; campaign persists them per-window.
"""
from __future__ import annotations

import functools
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

M, K, N = 8192, 1024, 4096
REPS = 8


def log(m):
    print(f"[int8bench] {m}", file=sys.stderr, flush=True)


def emit(rec):
    print(json.dumps(rec), flush=True)


def _force(out):
    np.asarray(jax.device_get(jax.tree_util.tree_leaves(out)[0])).ravel()[:1]


def timeit(fn, *args, iters=10):
    out = fn(*args)
    _force(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    _force(out)
    return (time.perf_counter() - t0) / iters * 1e3


def main():
    devs = jax.devices()
    log(f"backend {devs[0].platform} ({devs[0].device_kind})")
    fl = 2.0 * M * K * N * REPS

    # bf16 path
    a16 = jnp.full((M, K), 0.01, jnp.bfloat16)
    b16 = jnp.full((K, N), 0.01, jnp.bfloat16)

    @jax.jit
    def mm_bf16(a, b):
        def body(h, _):
            out = jax.lax.dot_general(h, b, (((1,), (0,)), ((), ())),
                                      preferred_element_type=jnp.float32)
            return out[:, :K].astype(jnp.bfloat16), None
        h, _ = jax.lax.scan(body, a, None, length=REPS)
        return h

    ms = timeit(mm_bf16, a16, b16)
    emit({"metric": "matmul_bf16", "ms": round(ms, 3),
          "tflops": round(fl / (ms * 1e-3) / 1e12, 1),
          "backend": devs[0].platform})

    # raw int8 path
    a8 = jnp.ones((M, K), jnp.int8)
    b8 = jnp.ones((K, N), jnp.int8)

    @jax.jit
    def mm_int8(a, b):
        def body(h, _):
            out = jax.lax.dot_general(h, b, (((1,), (0,)), ((), ())),
                                      preferred_element_type=jnp.int32)
            return jnp.clip(out[:, :K], -127, 127).astype(jnp.int8), None
        h, _ = jax.lax.scan(body, a, None, length=REPS)
        return h

    ms = timeit(mm_int8, a8, b8)
    emit({"metric": "matmul_int8", "ms": round(ms, 3),
          "tops": round(fl / (ms * 1e-3) / 1e12, 1),
          "backend": devs[0].platform})

    # full Int8Linear op (quant + int8 dot + dequant epilogue)
    from paddle_tpu.quantization.int8 import _int8_linear
    x = jnp.full((M, K), 0.5, jnp.float32)
    w_q = jnp.ones((K, N), jnp.int8)
    w_scale = jnp.ones((N,), jnp.float32)
    bias = jnp.zeros((N,), jnp.float32)

    raw = _int8_linear._raw_fn
    fn = jax.jit(lambda xx: raw(xx, w_q, bias, jnp.float32(1.0), w_scale))
    try:
        ms = timeit(fn, x)
        emit({"metric": "int8_linear_op", "ms": round(ms, 3),
              "tops": round(2.0 * M * K * N / (ms * 1e-3) / 1e12, 1),
              "backend": devs[0].platform})
    except Exception as e:
        emit({"metric": "int8_linear_op", "error": repr(e)[:160]})


if __name__ == "__main__":
    main()
