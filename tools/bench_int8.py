"""int8 vs bf16 matmul microbench row (round-3 verdict item 3: show the
real-int8 path's on-chip rate next to the bf16 MXU rate).

Times three variants of the serving matmul shape [B*S, D] @ [D, 4D]
chained through a lax.scan (one dispatch, the tunnel-latency rule from
CLAUDE.md):
  - bf16 @ bf16 -> f32 accumulate (the fp serving path)
  - int8 @ int8 -> i32 accumulate (raw MXU int8 rate)
  - the full Int8Linear op (quantize epilogue + int8 dot + dequant)
Emits one JSON line per variant; campaign persists them per-window.
"""
from __future__ import annotations

import functools
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(1, os.path.dirname(os.path.abspath(__file__)))

import jax
import jax.numpy as jnp
import numpy as np

M, K, N = 8192, 1024, 4096
REPS = 64      # hops per dispatch: ~4.4 TFLOP >> tunnel RTT work


def log(m):
    print(f"[int8bench] {m}", file=sys.stderr, flush=True)


def emit(rec):
    print(json.dumps(rec), flush=True)


from bench_util import chained_ms, force as _force  # noqa: E402


def main():
    devs = jax.devices()
    log(f"backend {devs[0].platform} ({devs[0].device_kind})")

    # all three micro rows run through chained_ms (CLAUDE.md: a single
    # [8192,1024]@[1024,4096] dispatch is single-digit-ms device work vs
    # ~70-170 ms tunnel RTT — the first version of this file measured
    # the tunnel). The slice back to [:, :K] adds one copy per hop to
    # BOTH paths, so the bf16-vs-int8 ratio is unaffected.
    fl_hop = 2.0 * M * K * N

    # bf16 path (1/K-weight row-mean keeps magnitudes neutral)
    b16 = jnp.full((K, N), 1.0 / K, jnp.bfloat16)
    ms = chained_ms(
        lambda h: jax.lax.dot_general(
            h, b16, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)[:, :K].astype(jnp.bfloat16),
        jnp.full((M, K), 0.5, jnp.bfloat16), length=REPS, iters=3)
    emit({"metric": "matmul_bf16", "ms": round(ms, 3),
          "tflops": round(fl_hop / (ms * 1e-3) / 1e12, 1),
          "backend": devs[0].platform})

    # raw int8 path
    b8 = jnp.ones((K, N), jnp.int8)
    ms = chained_ms(
        lambda h: jnp.clip(jax.lax.dot_general(
            h, b8, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.int32)[:, :K],
            -127, 127).astype(jnp.int8),
        jnp.ones((M, K), jnp.int8), length=REPS, iters=3)
    emit({"metric": "matmul_int8", "ms": round(ms, 3),
          "tops": round(fl_hop / (ms * 1e-3) / 1e12, 1),
          "backend": devs[0].platform})

    # full Int8Linear op (quant + int8 dot + dequant epilogue);
    # 1/K output scale keeps the f32 carry at 0.5 across hops
    from paddle_tpu.quantization.int8 import _int8_linear
    w_q = jnp.ones((K, N), jnp.int8)
    w_scale = jnp.full((N,), 1.0 / K, jnp.float32)
    bias = jnp.zeros((N,), jnp.float32)
    raw = _int8_linear._raw_fn
    try:
        ms = chained_ms(
            lambda h: raw(h, w_q, bias, jnp.float32(1.0),
                          w_scale)[:, :K].astype(jnp.float32),
            jnp.full((M, K), 0.5, jnp.float32), length=REPS, iters=3)
        emit({"metric": "int8_linear_op", "ms": round(ms, 3),
              "tops": round(fl_hop / (ms * 1e-3) / 1e12, 1),
              "backend": devs[0].platform})
    except Exception as e:
        emit({"metric": "int8_linear_op", "error": repr(e)[:160]})

    bench_decode(devs)


def bench_decode(devs):
    """KV-cache single-token decode, fp32 weights vs weight-only int8
    (incubate.FusedMultiTransformer.weight_only_quant) — decode is
    weight-HBM-bound, so int8 weights should approach a 4x step-time cut
    vs f32 on chip. The decode steps are CHAINED inside one jit via
    lax.scan (CLAUDE.md: per-dispatch tunnel latency is ~70-170 ms; an
    eager per-token loop would measure the tunnel, not the chip)."""
    import functools
    import paddle_tpu as paddle
    from paddle_tpu.incubate.nn import FusedMultiTransformer
    from paddle_tpu.incubate.fused_multi_transformer import _stack_forward
    paddle.seed(0)
    B, D, L, MAXLEN, STEPS = 8, 1024, 24, 1024, 16
    model = FusedMultiTransformer(embed_dim=D, num_heads=16,
                                  dim_feedforward=4 * D, num_layers=L)
    rng = np.random.RandomState(0)
    prefix = paddle.to_tensor(rng.randn(B, 512, D).astype(np.float32) * .1)
    x0 = jnp.asarray(rng.randn(B, 1, D).astype(np.float32) * .1)

    def decode_ms(m, caches, label):
        pv = [t._value for t in m._scan_inputs()]

        @jax.jit
        def chained(x, kc, vc, *pvv):
            def step(carry, t):
                x, kc, vc = carry
                y, kc, vc = _stack_forward(x, kc, vc, pvv, 512 + t,
                                           m.num_heads, m.head_dim,
                                           m.activation)
                return (y, kc, vc), None
            (y, kc, vc), _ = jax.lax.scan(
                step, (x, kc, vc), jnp.arange(STEPS))
            return y

        kc, vc = caches[0]._value, caches[1]._value
        out = chained(x0, kc, vc, *pv)
        _force(out)                                        # compile
        t0 = time.perf_counter()
        out = chained(x0, kc, vc, *pv)
        _force(out)
        ms = (time.perf_counter() - t0) / STEPS * 1e3
        emit({"metric": label, "ms_per_token": round(ms, 3),
              "chained_steps": STEPS, "backend": devs[0].platform})
        return ms

    try:
        caches = model.gen_cache(batch=B, max_len=MAXLEN)
        _, caches = model(prefix, caches=caches, time_step=0)
        fp_ms = decode_ms(model, caches, "decode_fp32")
        model.weight_only_quant()
        q_ms = decode_ms(model, caches, "decode_weight_only_int8")
        emit({"metric": "decode_speedup_int8_vs_fp32",
              "x": round(fp_ms / q_ms, 2), "backend": devs[0].platform})
    except Exception as e:
        emit({"metric": "decode_bench", "error": repr(e)[:200]})


if __name__ == "__main__":
    main()
