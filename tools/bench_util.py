"""Shared timing helpers for the measurement tools (ablate_step,
autotune_kernels, bench_int8). One copy of the tunnel-safe forcing rule:
block_until_ready can return early over the axon tunnel, so results are
forced with a host scalar pull (see CLAUDE.md / bench.py)."""
from __future__ import annotations

import time

import jax
import numpy as np


def force(out):
    """Genuinely wait for `out` (first leaf) by pulling a host scalar.
    Device execution is FIFO, so waiting on the last submission bounds
    the whole timed span."""
    leaf = jax.tree_util.tree_leaves(out)[0]
    np.asarray(jax.device_get(leaf)).ravel()[:1]


def timeit(fn, *args, iters=10, warmup=1):
    """Steady-state ms per call of fn(*args)."""
    for _ in range(warmup):
        force(fn(*args))
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    force(out)
    return (time.perf_counter() - t0) / iters * 1e3
