"""Shared timing helpers for the measurement tools (ablate_step,
autotune_kernels, bench_int8). One copy of the tunnel-safe forcing rule:
block_until_ready can return early over the axon tunnel, so results are
forced with a host scalar pull (see CLAUDE.md / bench.py)."""
from __future__ import annotations

import time

import jax
import numpy as np

# The roofline plausibility gate moved into the package
# (paddle_tpu/kernels/registry.py) so the kernel-selection registry's
# adoption path and the tools share ONE rule; re-exported here for the
# existing tool callers.
from paddle_tpu.kernels.registry import (  # noqa: F401
    FLOOR_GBS, FLOOR_TFLOPS, PEAK_BF16_TFLOPS, PEAK_HBM_GBS, gate_ms,
    plausible_ms)


def force(out):
    """Genuinely wait for `out` (first leaf) by pulling a host scalar.
    Device execution is FIFO, so waiting on the last submission bounds
    the whole timed span."""
    leaf = jax.tree_util.tree_leaves(out)[0]
    np.asarray(jax.device_get(leaf)).ravel()[:1]


def timeit(fn, *args, iters=10, warmup=1):
    """Steady-state ms per call of fn(*args).

    ONLY sound when one call's device time well exceeds the tunnel's
    per-dispatch RTT (~70-170 ms) — i.e. model-step-sized work. For
    kernel-sized work use chained_ms: the round-4 ablate/autotune calib
    rows measured the tunnel with this helper (e.g. 2.9 TF/s for a bf16
    matmul chain the model path drives at ~40 TF/s)."""
    for _ in range(warmup):
        force(fn(*args))
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    force(out)
    return (time.perf_counter() - t0) / iters * 1e3


def mix_grads(grads, dtype):
    """Fold a (dq, dk, dv) triple into one dq-shaped carry for
    chained_ms. Summing all three defeats jaxpr DCE — a dq-only carry
    lets the dkv kernel (a separate pallas_call / scan) be dropped from
    the timed chain. Assumes Sq == Skv so the shapes line up."""
    dq, dk, dv = grads
    return (dq + 1e-3 * dk + 1e-3 * dv).astype(dtype)


def chained_ms(step, carry, length=64, iters=3):
    """ms per application of `step`, amortizing dispatch latency.

    Runs `length` applications inside ONE jit as a lax.scan whose carry
    is the step's own output (data dependence defeats CSE), so per-call
    device time is length x kernel-time >> tunnel RTT; `iters` outer
    calls then pipeline like the model-step benches. `step` must map
    carry -> same shape/dtype carry."""
    run = jax.jit(lambda c: jax.lax.scan(
        lambda c, _: (step(c), None), c, None, length=length)[0])
    force(run(carry))                      # compile + warm
    t0 = time.perf_counter()
    for _ in range(iters):
        carry = run(carry)
    force(carry)
    return (time.perf_counter() - t0) / (iters * length) * 1e3
