"""Tier-1 runner: the suite in two fresh-process chunks, diffed.

The ROADMAP's single-process tier-1 command no longer fits this host's
870 s budget (PR-13 re-anchor note: a CLEAN worktree times out at
~82%, and one long single-process run segfaulted in jaxlib's CPU
backend_compile under memory pressure — chunked runs avoid both). This
tool IS the prescribed ritual, automated:

  python tools/run_tier1.py                  # both chunks + diff
  python tools/run_tier1.py --log /tmp/_t1.log
  python tools/run_tier1.py --timeout 900    # per-chunk ceiling

Each chunk runs `tests/test_[0-l]*.py` then `tests/test_[m-z]*.py` in
a FRESH python process with the tier-1 flags (`-q -m 'not slow'
--continue-on-collection-errors -p no:cacheprovider -p no:xdist
-p no:randomly`, JAX_PLATFORMS=cpu), the logs concatenate into ONE
tier-1 log (default /tmp/_t1.log — where chaos_drill --gate's
diff_failures leg looks), and tools/diff_failures.py compares the
combined FAILED/ERROR set against the stored baseline
(tests/baseline_failures_tier1.txt).

ONE exit code: 0 = both chunks completed (pytest rc 0/1 — baseline
failures are expected) AND zero NEW failures; 1 = new failures; 2 = a
chunk crashed/timed out/failed to collect (rc outside {0,1}) — a
timed-out chunk is NOT evidence of a regression, it is evidence the
budget is wrong for the host, and it exits distinctly so the caller
can tell.
"""
from __future__ import annotations

import argparse
import glob
import os
import subprocess
import sys
import time

HERE = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# [0-l] not [a-l]: test_67b_lowering.py starts with a digit — the
# PR-13 note's letter ranges would silently skip it, and diff_failures
# would misread its baselined failures as FIXED (and miss new ones)
CHUNKS = ("tests/test_[0-l]*.py", "tests/test_[m-z]*.py")
FLAGS = ["-q", "-m", "not slow", "--continue-on-collection-errors",
         "-p", "no:cacheprovider", "-p", "no:xdist", "-p", "no:randomly"]


def log(m: str) -> None:
    print(f"[tier1] {m}", file=sys.stderr, flush=True)


def run_chunk(pattern: str, timeout_s: int) -> tuple:
    """One fresh-process pytest chunk -> (rc, combined stdout+stderr).
    rc -9 marks a timeout kill."""
    files = sorted(glob.glob(os.path.join(HERE, pattern)))
    if not files:
        return 2, f"[tier1] chunk {pattern} matched no files\n"
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    cmd = [sys.executable, "-m", "pytest", *files, *FLAGS]
    t0 = time.time()
    try:
        res = subprocess.run(cmd, cwd=HERE, env=env,
                             stdout=subprocess.PIPE,
                             stderr=subprocess.STDOUT, timeout=timeout_s)
        rc, out = res.returncode, res.stdout.decode(errors="replace")
    except subprocess.TimeoutExpired as te:
        rc = -9
        out = ((te.stdout or b"").decode(errors="replace")
               + f"\n[tier1] chunk {pattern} TIMED OUT after "
                 f"{timeout_s}s\n")
    log(f"chunk {pattern}: rc={rc} in {time.time() - t0:.0f}s")
    return rc, out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--log", default="/tmp/_t1.log",
                    help="combined tier-1 log path (default /tmp/_t1.log"
                         " — where chaos_drill --gate looks)")
    ap.add_argument("--timeout", type=int, default=1500,
                    help="per-CHUNK wall ceiling, seconds (measured "
                         "2026-08-04: 493s + 1070s on a loaded host — "
                         "the historical 870s single-suite budget is "
                         "too tight even per chunk when the host is "
                         "busy; a timeout exits 2, an infra signal)")
    ap.add_argument("--baseline", default=None,
                    help="baseline file/log for diff_failures (default: "
                         "the stored tests/baseline_failures_tier1.txt)")
    args = ap.parse_args(argv)

    # the chunks must PARTITION the suite: a test file neither glob
    # matches would silently vanish from the gate
    all_files = set(glob.glob(os.path.join(HERE, "tests", "test_*.py")))
    covered = set()
    for pattern in CHUNKS:
        covered.update(glob.glob(os.path.join(HERE, pattern)))
    missing = sorted(os.path.basename(f) for f in all_files - covered)
    if missing:
        log(f"chunk globs MISS {missing} — fix CHUNKS")
        return 2

    logs, worst = [], 0
    for pattern in CHUNKS:
        rc, out = run_chunk(pattern, args.timeout)
        logs.append(out)
        if rc not in (0, 1):
            worst = 2      # crash/timeout/usage — not a failure diff
    with open(args.log, "w") as f:
        f.write("".join(logs))
    log(f"combined log -> {args.log}")
    if worst:
        log("a chunk did not complete; skipping the failure diff "
            "(rc=2 is an infrastructure signal, not a regression)")
        return worst

    sys.path.insert(0, os.path.join(HERE, "tools"))
    import diff_failures
    dargs = [args.log]
    if args.baseline:
        dargs += ["--baseline", args.baseline]
    return diff_failures.main(dargs)


if __name__ == "__main__":
    sys.exit(main())
