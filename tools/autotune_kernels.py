"""Populate the kernel-autotune cache from a real-TPU sweep (VERDICT r3
item 1b / weak #8).

Times every legal block-size candidate for the three production Pallas
kernels on the flagship bench shapes (GPT-350M: B=8 S=1024 H=16 D=64,
V=32768) and:
  - emits one JSON line per candidate (stdout; campaign salvages these),
  - writes the winners into the persistent autotune cache at
    perf/autotune.json (the repo-committed cache bench.py points
    PADDLE_TPU_AUTOTUNE_CACHE at), keyed exactly the way
    kernels/flash_attention._tuned_blocks builds its signature,
  - emits a final summary line with the winning blocks, so the shipped
    PADDLE_TPU_FLASH_BLOCK_* defaults can be updated by hand.

Run on the TPU-attached host: python tools/autotune_kernels.py
"""
from __future__ import annotations

import functools
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(1, os.path.dirname(os.path.abspath(__file__)))

import jax
import jax.numpy as jnp
import numpy as np

B, S, H, D = 8, 1024, 16, 64
V = 32768
CACHE_PATH = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "perf", "autotune.json")


def log(m):
    print(f"[autotune] {m}", file=sys.stderr, flush=True)


def emit(rec):
    print(json.dumps(rec), flush=True)


from bench_util import (chained_ms, force as _force,  # noqa: E402
                        gate_ms, mix_grads, timeit)

# Arithmetic/memory volume of ONE application on the sweep shapes, for
# the plausibility gate. Attention fwd: QK^T + PV at 2 flops/MAC, causal
# halves the work; bwd recomputes + 3 grad matmuls (~2.5x fwd). CE is
# HBM-bound: fwd reads the (T,V) logits once, bwd reads them again and
# writes dx.
FLASH_FWD_FLOPS = 2 * B * H * S * S * D
FLASH_BWD_FLOPS = 5 * B * H * S * S * D
CE_BYTES = 3 * (B * S) * V * 2


def _update_cache(key, value, window=None):
    os.makedirs(os.path.dirname(CACHE_PATH), exist_ok=True)
    try:
        with open(CACHE_PATH) as f:
            cache = json.load(f)
    except (OSError, ValueError):
        cache = {}
    cache[key] = value
    # provenance: which measurement window produced the current winners
    meta = cache.setdefault("_meta", {})
    meta[key] = {
        "window": window or os.environ.get("PADDLE_TPU_WINDOW", ""),
        "utc": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "gated": True,
    }
    tmp = f"{CACHE_PATH}.tmp{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(cache, f, indent=1)
    os.replace(tmp, CACHE_PATH)


def sweep_flash_fwd():
    from paddle_tpu.kernels.pallas_attention import mha_fwd
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    q = jax.random.normal(ks[0], (B, S, H, D), jnp.bfloat16)
    k = jax.random.normal(ks[1], (B, S, H, D), jnp.bfloat16)
    v = jax.random.normal(ks[2], (B, S, H, D), jnp.bfloat16)
    cands = [(bq, bk) for bq in (128, 256, 512) for bk in (128, 256, 512)]
    best = None
    for bq, bk in cands:
        try:
            # chained: kernel-sized work per dispatch sits far below the
            # tunnel RTT, so per-call timing measures the tunnel (the
            # first run of this sweep ranked candidates by RTT noise)
            ms = chained_ms(
                lambda qc: mha_fwd(qc, k, v, causal=True, block_q=bq,
                                   block_k=bk)[0].astype(q.dtype),
                q, length=32, iters=3)
        except Exception as e:
            emit({"kernel": "flash_fwd", "block_q": bq, "block_k": bk,
                  "error": repr(e)[:160]})
            continue
        bad = gate_ms(ms, flops=FLASH_FWD_FLOPS)
        emit({"kernel": "flash_fwd", "block_q": bq, "block_k": bk,
              "ms": round(ms, 3), **({"rejected": bad} if bad else {})})
        if bad:
            continue
        if best is None or ms < best[0]:
            best = (ms, bq, bk)
    if best:
        sig = f"B{B}_Sq{S}_Sk{S}_H{H}_D{D}_c1_bfloat16"
        _update_cache(f"flash_fwd::{sig}", [best[1], best[2]])
        emit({"kernel": "flash_fwd", "winner": [best[1], best[2]],
              "ms": round(best[0], 3)})
    return best


def sweep_flash_bwd():
    from paddle_tpu.kernels.pallas_attention import mha_bwd, mha_fwd
    ks = jax.random.split(jax.random.PRNGKey(0), 4)
    q = jax.random.normal(ks[0], (B, S, H, D), jnp.bfloat16)
    k = jax.random.normal(ks[1], (B, S, H, D), jnp.bfloat16)
    v = jax.random.normal(ks[2], (B, S, H, D), jnp.bfloat16)
    do = jax.random.normal(ks[3], (B, S, H, D), jnp.bfloat16)
    out, lse = jax.jit(functools.partial(mha_fwd, causal=True))(q, k, v)
    _force(out)
    # the r3 sweep measured the 128/128 Pallas bwd SLOWER than the
    # jax-level recompute bwd; this sweep answers whether any tile shape
    # beats it before the kernel earns its default back
    cands = [(128, 128), (128, 256), (256, 128), (256, 256), (512, 128),
             (128, 512), (256, 512), (512, 256), (512, 512)]
    best = None
    for bq, bk in cands:
        try:
            ms = chained_ms(
                lambda d: mix_grads(
                    mha_bwd(q, k, v, out, lse, d, causal=True,
                            block_q=bq, block_k=bk), do.dtype),
                do, length=32, iters=3)
        except Exception as e:
            emit({"kernel": "flash_bwd", "block_q": bq, "block_k": bk,
                  "error": repr(e)[:160]})
            continue
        bad = gate_ms(ms, flops=FLASH_BWD_FLOPS)
        emit({"kernel": "flash_bwd", "block_q": bq, "block_k": bk,
              "ms": round(ms, 3), **({"rejected": bad} if bad else {})})
        if bad:
            continue
        if best is None or ms < best[0]:
            best = (ms, bq, bk)
    # the jax-level recompute backward, same quantities, for the A/B
    from paddle_tpu.kernels.flash_attention import _flash_bwd
    ms = chained_ms(
        lambda d: mix_grads(
            _flash_bwd(q, k, v, out, lse, d, causal=True), do.dtype),
        do, length=32, iters=3)
    emit({"kernel": "flash_bwd_jaxlevel", "ms": round(ms, 3)})
    if best:
        sig = f"B{B}_Sq{S}_Sk{S}_H{H}_D{D}_c1_bfloat16"
        _update_cache(f"flash_bwd::{sig}", [best[1], best[2]])
        emit({"kernel": "flash_bwd", "winner": [best[1], best[2]],
              "ms": round(best[0], 3), "jaxlevel_ms": round(ms, 3)})
    return best


def sweep_ce():
    from paddle_tpu.kernels.pallas_ce import _ce_fwd, _ce_bwd
    ks = jax.random.split(jax.random.PRNGKey(0), 2)
    x = jax.random.normal(ks[0], (B * S, V), jnp.bfloat16)
    tgt = jax.random.randint(ks[1], (B * S,), 0, V)
    g = jnp.ones((B * S,), jnp.float32)
    cands = [(bt, bv) for bt in (128, 256) for bv in (512, 1024, 2048)]
    best = None
    for bt, bv in cands:
        def fwd_bwd(xc, bt=bt, bv=bv):
            # one application = fwd + bwd; dx has x's shape so it can
            # carry the chain (ranking uses the fwd+bwd total anyway)
            _, lse = _ce_fwd(xc, tgt, block_t=bt, block_v=bv)
            return _ce_bwd(xc, tgt, lse, g, block_t=bt,
                           block_v=bv).astype(x.dtype)
        try:
            tot = chained_ms(fwd_bwd, x, length=16, iters=3)
        except Exception as e:
            emit({"kernel": "ce", "block_t": bt, "block_v": bv,
                  "error": repr(e)[:160]})
            continue
        bad = gate_ms(tot, bytes_moved=CE_BYTES)
        emit({"kernel": "ce", "block_t": bt, "block_v": bv,
              "fwd_bwd_ms": round(tot, 3),
              **({"rejected": bad} if bad else {})})
        if bad:
            continue
        if best is None or tot < best[0]:
            best = (tot, bt, bv)
    if best:
        _update_cache(f"ce::T{B * S}_V{V}_bfloat16", [best[1], best[2]])
        emit({"kernel": "ce", "winner": [best[1], best[2]],
              "total_ms": round(best[0], 3)})
    return best


def main():
    devs = jax.devices()
    log(f"backend {devs[0].platform} ({devs[0].device_kind})")
    if devs[0].platform not in ("tpu", "axon"):
        log("not a TPU backend; refusing to populate the cache")
        sys.exit(17)
    for name, fn in (("flash_fwd", sweep_flash_fwd),
                     ("flash_bwd", sweep_flash_bwd), ("ce", sweep_ce)):
        log(f"=== {name} ===")
        try:
            fn()
        except Exception as e:
            emit({"kernel": name, "error": repr(e)[:200]})
            log(f"sweep {name} failed: {e!r}")


if __name__ == "__main__":
    main()
