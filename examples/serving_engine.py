"""Continuous-batching serving: mixed-length requests stream through a
fixed slot pool, joining and leaving mid-decode (inference/serving.py
— slot-pool KV cache, bucketed prefill, one jitted decode step).

    python examples/serving_engine.py
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

# examples demo on CPU devices by default (the machine's
# profile may preset JAX_PLATFORMS to a tunneled TPU);
# run with PADDLE_TPU_EXAMPLE_BACKEND=native for real chips
if os.environ.get("PADDLE_TPU_EXAMPLE_BACKEND", "cpu") == "cpu":
    from paddle_tpu.device import pin_cpu
    assert pin_cpu(1), "could not pin the CPU backend"

import numpy as np
import jax
import jax.numpy as jnp

from paddle_tpu.inference import ServingEngine
from paddle_tpu.models.gpt import GPTConfig, init_gpt_params


def main():
    cfg = GPTConfig(vocab_size=256, hidden_size=128, num_layers=4,
                    num_heads=8, max_seq_len=128, dtype=jnp.float32,
                    sequence_parallel=False, remat=False)
    params = init_gpt_params(cfg, jax.random.PRNGKey(0))
    eng = ServingEngine(params, cfg, family="gpt", num_slots=4,
                        max_len=128, max_top_k=16)

    rng = np.random.RandomState(0)
    # 8 requests, mixed prompt lengths and budgets, one sampled
    reqs = [eng.submit(rng.randint(0, 256, L).astype(np.int32),
                       max_new_tokens=g)
            for L, g in ((5, 12), (23, 8), (9, 16), (40, 6),
                         (3, 10), (17, 9), (11, 7), (6, 14))]
    reqs.append(eng.submit(rng.randint(0, 256, 8).astype(np.int32),
                           max_new_tokens=10, temperature=0.8,
                           top_k=16))

    tick = 0
    while eng.has_work():
        emitted = eng.step()
        tick += 1
        print(f"tick {tick:2d}: "
              + "  ".join(f"r{r.id}->{tok}" for r, tok in emitted))
    for r in reqs:
        print(f"req {r.id}: prompt_len={len(r.prompt)} "
              f"finish={r.finish_reason} tokens={r.tokens}")
    print("traces (decode, prefill):", eng.trace_counts())


if __name__ == "__main__":
    main()
