"""Static-graph training + inference export: the reference's classic
Program/Executor workflow, end to end.

    python examples/static_mnist.py
"""
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

# examples demo on CPU devices by default (the machine's
# profile may preset JAX_PLATFORMS to a tunneled TPU);
# run with PADDLE_TPU_EXAMPLE_BACKEND=native for real chips
if os.environ.get("PADDLE_TPU_EXAMPLE_BACKEND", "cpu") == "cpu":
    from paddle_tpu.device import pin_cpu
    assert pin_cpu(1), "could not pin the CPU backend"

import numpy as np

import paddle_tpu as paddle
import paddle_tpu.static as static


def main():
    paddle.enable_static()
    main_prog, startup = static.Program(), static.Program()
    with static.program_guard(main_prog, startup):
        img = static.data("img", [-1, 784], "float32")
        label = static.data("label", [-1], "int64")
        hidden = static.nn.fc(img, 128, activation="relu")
        logits = static.nn.fc(hidden, 10)
        loss = paddle.nn.functional.cross_entropy(logits, label)
        paddle.optimizer.Adam(learning_rate=1e-3).minimize(loss)

    exe = static.Executor()
    with static.program_guard(main_prog, startup):
        exe.run(startup)

    rng = np.random.RandomState(0)
    X = rng.randn(256, 784).astype(np.float32)
    Y = rng.randint(0, 10, 256).astype(np.int64)
    for epoch in range(15):
        lv, = exe.run(main_prog, feed={"img": X, "label": Y},
                      fetch_list=[loss])
    print(f"final train loss: {float(lv):.4f}")

    # export the inference slice (training ops pruned) and serve it
    d = tempfile.mkdtemp()
    path = os.path.join(d, "mnist")
    static.save_inference_model(path, [img], [logits], exe,
                                program=main_prog)
    layer, feeds, fetches = static.load_inference_model(path, exe)
    out, = exe.run(layer, feed={"img": X[:5]}, fetch_list=fetches)
    print("served logits shape:", out.shape)

    from paddle_tpu.inference import Config, create_predictor
    pred = create_predictor(Config(path + ".pdmodel", path + ".pdiparams"))
    h = pred.get_input_handle(pred.get_input_names()[0])
    h.copy_from_cpu(X[:3])
    pred.run()
    print("predictor output shape:",
          pred.get_output_handle(pred.get_output_names()[0])
          .copy_to_cpu().shape)
    paddle.disable_static()


if __name__ == "__main__":
    main()
