"""Fine-tune the BERT encoder for sequence classification, from raw
strings: FasterTokenizer (native WordPiece) → bert_encode → pooled
classifier — the text stack end-to-end.

    python examples/finetune_bert_classifier.py
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

# examples demo on CPU devices by default (the machine's
# profile may preset JAX_PLATFORMS to a tunneled TPU);
# run with PADDLE_TPU_EXAMPLE_BACKEND=native for real chips
if os.environ.get("PADDLE_TPU_EXAMPLE_BACKEND", "cpu") == "cpu":
    from paddle_tpu.device import pin_cpu
    assert pin_cpu(1), "could not pin the CPU backend"

import functools
import numpy as np
import jax
import jax.numpy as jnp
import optax

from paddle_tpu.text import FasterTokenizer
from paddle_tpu.models.bert import (BertConfig, init_bert_params,
                                    init_cls_head, bert_cls_loss)

SENTENCES = [
    ("the movie was great fun", 1), ("a lazy boring film", 0),
    ("great acting and fun plot", 1), ("boring and lazy writing", 0),
    ("fun from start to finish", 1), ("a great watch", 1),
    ("lazy plot , boring cast", 0), ("boring , skip it", 0),
]
VOCAB = ["[PAD]", "[UNK]", "[CLS]", "[SEP]", "the", "movie", "was",
         "great", "fun", "a", "lazy", "boring", "film", "acting", "and",
         "plot", "from", "start", "to", "finish", "watch", "cast",
         "writing", "skip", "it", ","]


def main():
    tok = FasterTokenizer({t: i for i, t in enumerate(VOCAB)})
    enc = tok([s for s, _ in SENTENCES], max_seq_len=12)
    labels = jnp.asarray([y for _, y in SENTENCES])
    batch = {"tokens": jnp.asarray(enc["input_ids"]),
             "attention_mask": jnp.asarray(enc["attention_mask"]),
             "labels": labels}

    cfg = BertConfig(vocab_size=len(VOCAB), hidden_size=64, num_layers=2,
                     num_heads=4, max_seq_len=32, dtype=jnp.float32)
    params = init_bert_params(cfg, jax.random.PRNGKey(0))
    head = init_cls_head(cfg, 2, jax.random.PRNGKey(1))

    def loss_fn(both, batch):
        return bert_cls_loss(both[0], both[1], batch, cfg)

    opt = optax.adam(5e-3)
    both = (params, head)
    state = opt.init(both)
    lf = jax.jit(loss_fn)
    gf = jax.jit(jax.grad(loss_fn))
    for it in range(30):
        g = gf(both, batch)
        upd, state = opt.update(g, state)
        both = jax.tree_util.tree_map(lambda p, u: p + u, both, upd)
        if it % 10 == 0:
            print(f"step {it}: loss={float(lf(both, batch)):.4f}")
    print(f"final loss={float(lf(both, batch)):.4f}")


if __name__ == "__main__":
    main()
