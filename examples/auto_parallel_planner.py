"""Let the auto-parallel planner pick the hybrid assignment, then train
on the mesh it chose — the reference parallel_tuner workflow
(distributed/auto_parallel/static/tuner/parallel_tuner.py) collapsed to
three calls: plan -> build mesh -> jit the step.

Run on any host (8 virtual CPU devices by default):
    python examples/auto_parallel_planner.py
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

if os.environ.get("PADDLE_TPU_EXAMPLE_BACKEND", "cpu") == "cpu":
    from paddle_tpu.device import pin_cpu
    assert pin_cpu(8), "could not pin the CPU backend"

import functools

import numpy as np
import jax
import jax.numpy as jnp

from paddle_tpu.cost_model import rank_parallel_plans
from paddle_tpu.models.gpt import (GPTConfig, PARAM_SPECS,
                                   init_gpt_params, init_opt_state,
                                   shard_gpt_params, train_step)
from paddle_tpu.parallel.mesh import P, build_mesh, sharding_for, use_mesh

BATCH, SEQ = 16, 64

cfg = GPTConfig(vocab_size=512, hidden_size=128, num_layers=4,
                num_heads=8, max_seq_len=SEQ, dtype=jnp.float32,
                param_dtype=jnp.float32, remat=False,
                remat_policy="none", sequence_parallel=False)

# 1. rank every legal (dp, mp, pp, fsdp) assignment by the cost model
plans = rank_parallel_plans(cfg, n_devices=jax.device_count(),
                            global_batch=BATCH)
print("top-3 assignments:")
for p in plans[:3]:
    print("  ", p)
plan = plans[0]

# 2. build the chosen mesh and lay the model out on it
mesh = build_mesh(plan.mesh_axes())
with use_mesh(mesh):
    params = shard_gpt_params(
        init_gpt_params(cfg, jax.random.PRNGKey(0)), mesh)
    opt = init_opt_state(params)
    # batch shards over whatever data-style axes the PLAN carries (the
    # cost model prices batch over dp x fsdp) — hardcoding 'dp' would
    # silently under-shard a dp x fsdp or fsdp-led plan
    batch_axes = tuple(a for a in ("dp", "fsdp")
                       if a in plan.mesh_axes()) or None
    tokens = jax.device_put(
        jnp.asarray(np.random.randint(0, 512, (BATCH, SEQ + 1)),
                    jnp.int32),
        sharding_for(P(batch_axes, None), mesh))

    # 3. one jit: GSPMD partitions the step per the planner's layout
    step = jax.jit(functools.partial(train_step, cfg=cfg, lr=1e-3))
    for i in range(3):
        loss, params, opt = step(params, opt, tokens)
        print(f"step {i}: loss {float(loss):.4f}")

print(f"trained on planner-chosen {plan} (times at TPU constants)")
