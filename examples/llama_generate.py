"""Greedy generation with the Llama-family decoder: grouped-query
attention shrinks the KV cache (and decode HBM traffic) by
num_heads / num_kv_heads with no change to the decode loop.

Run: python examples/llama_generate.py
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

if os.environ.get("PADDLE_TPU_EXAMPLE_BACKEND", "cpu") == "cpu":
    from paddle_tpu.device import pin_cpu
    assert pin_cpu(1), "could not pin the CPU backend"

import numpy as np
import jax
import jax.numpy as jnp

from paddle_tpu.models.llama import (LlamaConfig, greedy_generate,
                                     init_kv_cache, init_llama_params)

cfg = LlamaConfig(vocab_size=512, hidden_size=128, num_layers=4,
                  num_heads=8, num_kv_heads=2, max_seq_len=128,
                  dtype=jnp.float32, param_dtype=jnp.float32,
                  remat=False)
params = init_llama_params(cfg, jax.random.PRNGKey(0))

prompt = jnp.asarray(
    np.random.default_rng(0).integers(0, 512, (2, 8)), jnp.int32)
out = greedy_generate(params, prompt, cfg, max_new_tokens=16)
print(f"prompt {prompt.shape} -> generated {out.shape}")
print("sequences:", np.asarray(out)[:, :12], "...")

# the GQA saving, concretely: cache bytes vs an MHA cache
mha = init_kv_cache(LlamaConfig(**{**cfg.__dict__,
                                   "num_kv_heads": cfg.num_heads}),
                    2, 24)
gqa = init_kv_cache(cfg, 2, 24)
ratio = (mha["k"].size + mha["v"].size) / (gqa["k"].size + gqa["v"].size)
print(f"KV cache shrink vs MHA: {ratio:.0f}x "
      f"({cfg.num_heads} heads -> {cfg.num_kv_heads} kv heads)")
