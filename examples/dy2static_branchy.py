"""dy2static: dygraph code with data-dependent Python control flow
compiles to ONE XLA graph (reference
python/paddle/jit/dy2static/ast_transformer.py workflow).

    python examples/dy2static_branchy.py
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

if os.environ.get("PADDLE_TPU_EXAMPLE_BACKEND", "cpu") == "cpu":
    from paddle_tpu.device import pin_cpu
    assert pin_cpu(1), "could not pin the CPU backend"

import numpy as np

import paddle_tpu as paddle
import paddle_tpu.nn as nn


class GatedNet(nn.Layer):
    """Forward branches on a runtime statistic of the input — classic
    dygraph style the reference converts with its AST transformers."""

    def __init__(self):
        super().__init__()
        self.hot = nn.Linear(8, 8)
        self.cold = nn.Linear(8, 8)
        self.head = nn.Linear(8, 2)

    def forward(self, x):
        # tensor-dependent if: becomes lax.cond inside the graph
        if x.abs().mean() > 1.0:
            h = self.hot(x)
        else:
            h = self.cold(x)
        # tensor-dependent loop: becomes lax.while_loop
        steps = paddle.to_tensor(np.int32(0))
        while h.abs().max() > 3.0:
            h = h * 0.5
            steps = steps + 1
        return self.head(h)


def main():
    paddle.seed(0)
    net = GatedNet()
    sf = paddle.jit.to_static(net.forward)

    small = paddle.to_tensor(np.full((4, 8), 0.1, np.float32))
    large = paddle.to_tensor(np.full((4, 8), 9.0, np.float32))

    for name, batch in (("small", small), ("large", large)):
        eager = net(batch).numpy()            # plain dygraph
        compiled = sf(batch).numpy()          # one compiled graph
        np.testing.assert_allclose(compiled, eager, rtol=1e-5, atol=1e-5)
        print(f"{name}: compiled == eager, out[0] = {compiled[0]}")

    # both inputs hit the SAME compiled specialization: the branch and
    # the loop live inside the graph, not in Python
    assert len(sf.program_cache) == 1
    print("one graph, data-dependent control flow inside: OK")


if __name__ == "__main__":
    main()
