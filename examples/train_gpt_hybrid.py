"""Train the GPT flagship under hybrid parallelism (dp × mp) on a device
mesh — the fleet API end-to-end.

Run on any host (8 virtual CPU devices by default):
    python examples/train_gpt_hybrid.py
On a TPU pod slice the same code uses the real chips; scale the degrees
in `hybrid_configs` to the topology.
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

# examples demo on CPU devices by default (the machine's
# profile may preset JAX_PLATFORMS to a tunneled TPU);
# run with PADDLE_TPU_EXAMPLE_BACKEND=native for real chips
if os.environ.get("PADDLE_TPU_EXAMPLE_BACKEND", "cpu") == "cpu":
    from paddle_tpu.device import pin_cpu
    assert pin_cpu(8), "could not pin the CPU backend"

import numpy as np
import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu.distributed import fleet
from paddle_tpu.parallel.mesh import build_mesh, use_mesh, shard_value
from paddle_tpu.models.gpt import (GPTConfig, init_gpt_params,
                                   init_opt_state, train_step,
                                   shard_gpt_params)


def main():
    # 1) topology: dp=2 × mp=4 over 8 devices (pp/sp/ep available too)
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 2, "mp_degree": 4}
    hcg = fleet.init(is_collective=True, strategy=strategy)
    mesh = hcg.mesh
    print("mesh:", dict(mesh.shape))

    # 2) the functional core: stacked params, declarative shardings
    cfg = GPTConfig(vocab_size=1024, hidden_size=256, num_layers=4,
                    num_heads=8, max_seq_len=128, dtype=jnp.bfloat16,
                    remat=False, sequence_parallel=True)
    with use_mesh(mesh):
        params = shard_gpt_params(init_gpt_params(
            cfg, jax.random.PRNGKey(0)), mesh)
        opt_state = init_opt_state(params)
        from paddle_tpu.models.facade import make_train_step
        step = make_train_step(train_step, cfg=cfg, lr=1e-3)
        rng = np.random.RandomState(0)
        for it in range(5):
            tokens = jnp.asarray(rng.randint(
                0, cfg.vocab_size, (8, cfg.max_seq_len + 1)))
            loss, params, opt_state = step(params, opt_state, tokens)
            print(f"step {it}: loss={float(loss):.4f}")


if __name__ == "__main__":
    main()
