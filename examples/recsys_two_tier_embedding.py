"""Two-tier recsys embeddings: hot slots in a device-resident dense
table, the unbounded long-tail in the host-resident sparse spill tier —
the parameter-server workload mapped onto one TPU host
(docs/ps_embedding_on_tpu.md; reference
paddle/fluid/distributed/ps/table/memory_sparse_table.cc + the
DownpourWorker pull/compute/push loop).

Run: python examples/recsys_two_tier_embedding.py
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

if os.environ.get("PADDLE_TPU_EXAMPLE_BACKEND", "cpu") == "cpu":
    from paddle_tpu.device import pin_cpu
    assert pin_cpu(1), "could not pin the CPU backend"

import numpy as np
import jax
import jax.numpy as jnp

from paddle_tpu.incubate import HostShardedEmbedding
from paddle_tpu.parallel.dist_tail import CountFilterEntry

DIM, HOT_VOCAB, BATCH = 16, 1000, 64
rng = np.random.default_rng(0)

# hot tier: dense table in device memory (at scale: mesh-sharded
# VocabParallelEmbedding); cold tier: host arena with admission — a
# long-tail id must be seen twice before it earns a row
hot = jnp.asarray(rng.normal(0, 0.05, (HOT_VOCAB, DIM)), jnp.float32)
cold = HostShardedEmbedding(DIM, lr=0.1, optimizer="adagrad",
                            entry=CountFilterEntry(2), seed=1)
w = jnp.asarray(rng.normal(0, 0.1, (2 * DIM,)), jnp.float32)

# CTR-ish batches: one hot slot + one long-tail slot per example
hot_ids = rng.integers(0, HOT_VOCAB, (BATCH,))
tail_ids = rng.integers(1_000_000_000, 1_000_000_200, (BATCH,))
clicks = jnp.asarray(rng.integers(0, 2, (BATCH,)), jnp.float32)


def loss_fn(hot_tab, cold_rows, w):
    feat = jnp.concatenate([hot_tab[hot_ids], cold_rows], -1)
    logits = feat @ w
    # numerically-stable BCE-with-logits
    return jnp.mean(jnp.maximum(logits, 0) - logits * clicks
                    + jnp.log1p(jnp.exp(-jnp.abs(logits))))


grad_fn = jax.value_and_grad(loss_fn, argnums=(0, 1, 2))
for step in range(20):
    rows = cold.pull(tail_ids)               # PS "pull"
    loss, (g_hot, g_cold, g_w) = grad_fn(hot, rows, w)
    hot = hot - 0.1 * g_hot                  # dense tier: device update
    w = w - 0.1 * g_w
    cold.push(tail_ids, np.asarray(g_cold))  # PS "push" (host rule)
    if step % 5 == 0:
        print(f"step {step}: loss {float(loss):.4f}, "
              f"{len(cold)} tail rows admitted")

print(f"final loss {float(loss):.4f}; cold tier holds {len(cold)} rows "
      f"of an unbounded id space")
state = cold.state_dict()
print(f"checkpointable: {state['ids'].shape[0]} rows, "
      f"dim {state['dim']}")
