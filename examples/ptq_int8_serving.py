"""Post-training quantization to REAL int8 serving, end to end
(reference workflow:
python/paddle/static/quantization/post_training_quantization.py — here
the int8 kernels are XLA int8 dot_general/conv on the MXU):

train fp32 -> PTQ calibrate -> convert_to_int8 -> serve via to_static.

    python examples/ptq_int8_serving.py
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

if os.environ.get("PADDLE_TPU_EXAMPLE_BACKEND", "cpu") == "cpu":
    from paddle_tpu.device import pin_cpu
    assert pin_cpu(1), "could not pin the CPU backend"

import numpy as np

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.quantization import PTQ, QuantConfig


class ConvNet(nn.Layer):
    def __init__(self):
        super().__init__()
        self.conv = nn.Conv2D(1, 8, 3, padding=1)
        self.relu = nn.ReLU()
        self.pool = nn.AdaptiveAvgPool2D(1)
        self.head = nn.Linear(8, 10)

    def forward(self, x):
        h = self.pool(self.relu(self.conv(x)))
        return self.head(h.reshape([h.shape[0], 8]))


def main():
    paddle.seed(0)
    rng = np.random.RandomState(0)
    xs = rng.randn(256, 1, 8, 8).astype(np.float32)
    w_true = rng.randn(8 * 8, 10).astype(np.float32)
    ys = np.argmax(xs.reshape(256, -1)[:, :64] @ w_true, -1).astype(
        np.int64)

    # 1. a briefly trained fp model
    net = ConvNet()
    opt = paddle.optimizer.Adam(learning_rate=3e-3,
                                parameters=net.parameters())
    loss_fn = nn.CrossEntropyLoss()
    for i in range(0, 256, 64):
        loss = loss_fn(net(paddle.to_tensor(xs[i:i + 64])),
                       paddle.to_tensor(ys[i:i + 64]))
        loss.backward()
        opt.step()
        opt.clear_grad()
    net.eval()
    fp_pred = np.argmax(net(paddle.to_tensor(xs)).numpy(), -1)

    # 2. PTQ: wrap + calibrate on representative batches
    ptq = PTQ(QuantConfig())
    ptq.quantize(net)
    for i in range(0, 256, 64):
        net(paddle.to_tensor(xs[i:i + 64]))

    # 3. freeze into REAL int8 layers (int8 weights, int8 matmul/conv)
    int8_net = ptq.convert(net, to_int8=True)
    q_pred = np.argmax(int8_net(paddle.to_tensor(xs)).numpy(), -1)
    agree = float((q_pred == fp_pred).mean())
    print(f"int8 vs fp top-1 agreement: {agree:.3f}")
    assert agree >= 0.98, agree

    # 4. serve the int8 model as ONE compiled graph
    served = paddle.jit.to_static(lambda t: int8_net(t))
    out = served(paddle.to_tensor(xs[:16]))
    np.testing.assert_allclose(
        out.numpy(),
        int8_net(paddle.to_tensor(xs[:16])).numpy(), rtol=1e-5, atol=1e-5)
    print("int8 serving graph OK:", out.shape)


if __name__ == "__main__":
    main()
