"""Detection-head fine-tune on synthetic boxes: MobileNet backbone +
YOLO head trained with paddle.vision.ops.yolo_loss, decoded with
yolo_box, de-duplicated with matrix_nms.

    python examples/finetune_detection_head.py
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

# examples demo on CPU devices by default (the machine's profile may
# preset JAX_PLATFORMS to a tunneled TPU)
if os.environ.get("PADDLE_TPU_EXAMPLE_BACKEND", "cpu") == "cpu":
    from paddle_tpu.device import pin_cpu
    assert pin_cpu(1), "could not pin the CPU backend"

import numpy as np

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.vision import ops

CLASSES = 3
ANCHORS = [16, 16, 32, 32]
MASK = [0, 1]
IMG = 64
DOWNSAMPLE = 16


class TinyDetector(nn.Layer):
    """A small conv backbone + the YOLO head conv."""

    def __init__(self):
        super().__init__()
        self.backbone = nn.Sequential(
            nn.Conv2D(3, 16, 3, 2, 1), nn.ReLU(),
            nn.Conv2D(16, 32, 3, 2, 1), nn.ReLU(),
            nn.Conv2D(32, 32, 3, 2, 1), nn.ReLU(),
            nn.Conv2D(32, 32, 3, 2, 1), nn.ReLU())
        self.head = nn.Conv2D(32, len(MASK) * (5 + CLASSES), 1)

    def forward(self, x):
        return self.head(self.backbone(x))


def synthetic_batch(rng, batch=4):
    """Images with one bright square each; the box is the target."""
    imgs = rng.rand(batch, 3, IMG, IMG).astype(np.float32) * 0.1
    boxes = np.zeros((batch, 1, 4), np.float32)
    labels = np.zeros((batch, 1), np.int64)
    for i in range(batch):
        cx, cy = rng.uniform(0.3, 0.7, 2)
        w = h = rng.uniform(0.2, 0.4)
        x0 = int((cx - w / 2) * IMG)
        y0 = int((cy - h / 2) * IMG)
        x1 = int((cx + w / 2) * IMG)
        y1 = int((cy + h / 2) * IMG)
        cls = rng.randint(0, CLASSES)
        imgs[i, cls, y0:y1, x0:x1] = 1.0
        boxes[i, 0] = [cx, cy, w, h]
        labels[i, 0] = cls
    return imgs, boxes, labels


def main():
    paddle.seed(0)
    rng = np.random.RandomState(0)
    net = TinyDetector()
    opt = paddle.optimizer.Adam(learning_rate=3e-4,
                                parameters=net.parameters())

    losses = []
    for step in range(16):
        imgs, boxes, labels = synthetic_batch(rng)
        pred = net(paddle.to_tensor(imgs))
        loss = ops.yolo_loss(
            pred, paddle.to_tensor(boxes), paddle.to_tensor(labels),
            ANCHORS, MASK, CLASSES, ignore_thresh=0.7,
            downsample_ratio=DOWNSAMPLE, use_label_smooth=False).sum()
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss.numpy()))
        if step % 4 == 0:
            print(f"step {step}: yolo_loss {losses[-1]:.3f}")
    # robust gate: mean of the last quarter under mean of the first
    head = float(np.mean(losses[:4]))
    tail = float(np.mean(losses[-4:]))
    print(f"loss {head:.3f} -> {tail:.3f}")
    assert tail < head, (head, tail)

    # decode + nms on one image
    imgs, _boxes, _labels = synthetic_batch(rng, batch=1)
    pred = net(paddle.to_tensor(imgs))
    bxs, scores = ops.yolo_box(
        pred, paddle.to_tensor(np.array([[IMG, IMG]], np.int32)),
        [ANCHORS[2 * i + j] for i in MASK for j in (0, 1)], CLASSES,
        conf_thresh=0.0, downsample_ratio=DOWNSAMPLE)
    out, nums = ops.matrix_nms(
        bxs.reshape([1, -1, 4]),
        paddle.to_tensor(np.transpose(scores.numpy(), (0, 2, 1))),
        score_threshold=0.0, post_threshold=0.0, nms_top_k=10,
        keep_top_k=5, background_label=-1)
    print(f"kept {int(nums.numpy()[0])} detections; "
          f"top: {out.numpy()[0][:2]}")
    print("detection example OK")


if __name__ == "__main__":
    main()
