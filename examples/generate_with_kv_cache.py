"""Greedy text generation through the KV cache (prefill + single-token
decode steps under one jit) — the inference decoder path.

    python examples/generate_with_kv_cache.py
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

# examples demo on CPU devices by default (the machine's
# profile may preset JAX_PLATFORMS to a tunneled TPU);
# run with PADDLE_TPU_EXAMPLE_BACKEND=native for real chips
if os.environ.get("PADDLE_TPU_EXAMPLE_BACKEND", "cpu") == "cpu":
    from paddle_tpu.device import pin_cpu
    assert pin_cpu(1), "could not pin the CPU backend"

import numpy as np
import jax
import jax.numpy as jnp

from paddle_tpu.models.gpt import (GPTConfig, init_gpt_params,
                                   greedy_generate)


def main():
    cfg = GPTConfig(vocab_size=256, hidden_size=128, num_layers=4,
                    num_heads=8, max_seq_len=64, dtype=jnp.float32,
                    sequence_parallel=False, remat=False)
    params = init_gpt_params(cfg, jax.random.PRNGKey(0))
    prompt = jnp.asarray(
        np.random.RandomState(0).randint(0, 256, (2, 8)), jnp.int32)
    out = greedy_generate(params, prompt, cfg, max_new_tokens=16)
    print("prompt :", np.asarray(prompt))
    print("decoded:", np.asarray(out[:, 8:]))


if __name__ == "__main__":
    main()
