"""Seq2seq with beam-search decoding: a GRU encoder-decoder learns to
reverse short digit sequences; decoding runs through
nn.BeamSearchDecoder + nn.dynamic_decode (the reference's decode.py
workflow).

    python examples/seq2seq_beam_search.py
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

if os.environ.get("PADDLE_TPU_EXAMPLE_BACKEND", "cpu") == "cpu":
    from paddle_tpu.device import pin_cpu
    assert pin_cpu(1), "could not pin the CPU backend"

import numpy as np

import paddle_tpu as paddle
import paddle_tpu.nn as nn

V = 12            # 0=pad/end, 1=start, 2..11 digits
START, END = 1, 0
SEQ = 4
HID = 64


class Seq2Seq(nn.Layer):
    def __init__(self):
        super().__init__()
        self.embed = nn.Embedding(V, HID)
        self.encoder = nn.GRU(HID, HID)
        self.cell = nn.GRUCell(HID, HID)
        self.out = nn.Linear(HID, V)

    def encode(self, src):
        x = self.embed(src)
        _out, h = self.encoder(x)
        return h[0]                      # [B, HID]

    def decode_step(self, tok, state):
        x = self.embed(tok)
        out, new_state = self.cell(x, state)
        return self.out(out), new_state


def batch(rng, n=32):
    src = rng.randint(2, V, (n, SEQ)).astype(np.int64)
    tgt = src[:, ::-1].copy()
    return src, tgt


def main():
    paddle.seed(0)
    rng = np.random.RandomState(0)
    net = Seq2Seq()
    opt = paddle.optimizer.Adam(learning_rate=3e-3,
                                parameters=net.parameters())
    ce = nn.CrossEntropyLoss()

    first = last = None
    for step in range(30):
        src, tgt = batch(rng)
        state = net.encode(paddle.to_tensor(src))
        toks = np.concatenate(
            [np.full((len(src), 1), START, np.int64), tgt[:, :-1]], 1)
        loss = 0.0
        for t in range(SEQ):
            logits, state = net.decode_step(
                paddle.to_tensor(toks[:, t]), state)
            loss = loss + ce(logits, paddle.to_tensor(tgt[:, t]))
        loss.backward()
        opt.step()
        opt.clear_grad()
        v = float(loss.numpy())
        first = v if first is None else first
        last = v
        if step % 10 == 0:
            print(f"step {step}: loss {v:.3f}")
    assert last < first * 0.7, (first, last)

    # beam-search decode through the Decoder protocol
    class CellAdapter:
        def __call__(self, inputs, states):
            return net.decode_step(inputs, states)

    decoder = nn.BeamSearchDecoder(
        CellAdapter(), start_token=START, end_token=END, beam_size=3,
        embedding_fn=None)
    src, tgt = batch(rng, n=2)
    # initialize() tiles the [B, ...] encoder state to the beam itself
    init_state = net.encode(paddle.to_tensor(src))
    outs, final = nn.dynamic_decode(decoder, inits=init_state,
                                    max_step_num=SEQ + 1)
    ids = outs.numpy()[:, :SEQ, 0]                 # best beam
    acc = (ids == tgt).mean()
    print(f"beam-search reversal accuracy: {acc:.2f}")
    assert acc > 0.5, acc
    print("seq2seq beam search OK")


if __name__ == "__main__":
    main()
